"""paddle.vision.ops (reference: python/paddle/vision/ops.py — yolo_loss:36,
yolo_box:247, deform_conv2d:418, DeformConv2D:621, read_file:810,
decode_jpeg:855; CUDA kernels in operators/detection/yolov3_loss_op.*,
yolo_box_op.*, deformable_conv_op.*).

TPU-native design: everything is expressed as dense jax.numpy tensor math —
target assignment via scatter (`.at[]`), bilinear sampling via gathers — so
the whole op jit-compiles and fuses; no per-box host loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer
from ..nn import initializer as I


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# ---------------------------------------------------------------------------
# YOLO box decode
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head output into boxes+scores
    (reference vision/ops.py:247; kernel operators/detection/yolo_box_op.h).

    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w) int.
    Returns boxes [N, A*H*W, 4] (x1,y1,x2,y2 in image scale) and
    scores [N, A*H*W, C]; predictions with objectness < conf_thresh zeroed.
    """
    x = jnp.asarray(x)
    img_size = jnp.asarray(img_size)
    n, c, h, w = x.shape
    an = len(anchors) // 2
    assert c == an * (5 + class_num), "channel/anchor mismatch"
    anchors_wh = jnp.asarray(anchors, jnp.float32).reshape(an, 2)

    pred = x.reshape(n, an, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)

    bx = (_sigmoid(pred[:, :, 0]) * alpha + beta + grid_x) / w
    by = (_sigmoid(pred[:, :, 1]) * alpha + beta + grid_y) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    bw = jnp.exp(pred[:, :, 2]) * anchors_wh[:, 0].reshape(1, an, 1, 1) / input_w
    bh = jnp.exp(pred[:, :, 3]) * anchors_wh[:, 1].reshape(1, an, 1, 1) / input_h

    conf = _sigmoid(pred[:, :, 4])
    keep = (conf >= conf_thresh).astype(x.dtype)
    conf = conf * keep
    scores = _sigmoid(pred[:, :, 5:]) * conf[:, :, None]

    img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
    img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, img_w - 1)
        y1 = jnp.clip(y1, 0.0, img_h - 1)
        x2 = jnp.clip(x2, 0.0, img_w - 1)
        y2 = jnp.clip(y2, 0.0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, an * h * w, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, an * h * w, class_num)
    return boxes, scores


# ---------------------------------------------------------------------------
# YOLOv3 loss
# ---------------------------------------------------------------------------
def _box_iou_xywh(b1, b2):
    """IoU of center-format boxes; b1 [..., 4], b2 [..., 4] broadcastable."""
    b1x1, b1x2 = b1[..., 0] - b1[..., 2] / 2, b1[..., 0] + b1[..., 2] / 2
    b1y1, b1y2 = b1[..., 1] - b1[..., 3] / 2, b1[..., 1] + b1[..., 3] / 2
    b2x1, b2x2 = b2[..., 0] - b2[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2
    b2y1, b2y2 = b2[..., 1] - b2[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2
    ix = jnp.maximum(
        jnp.minimum(b1x2, b2x2) - jnp.maximum(b1x1, b2x1), 0.0)
    iy = jnp.maximum(
        jnp.minimum(b1y2, b2y2) - jnp.maximum(b1y1, b2y1), 0.0)
    inter = ix * iy
    a1 = jnp.maximum(b1x2 - b1x1, 0.0) * jnp.maximum(b1y2 - b1y1, 0.0)
    a2 = jnp.maximum(b2x2 - b2x1, 0.0) * jnp.maximum(b2y2 - b2y1, 0.0)
    return inter / jnp.maximum(a1 + a2 - inter, 1e-10)


def _bce(logit, target):
    return jnp.maximum(logit, 0) - logit * target + jnp.log1p(
        jnp.exp(-jnp.abs(logit)))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference vision/ops.py:36; kernel
    operators/detection/yolov3_loss_op.h).

    x: [N, A*(5+C), H, W]; gt_box: [N, B, 4] (cx,cy,w,h normalized);
    gt_label: [N, B] int; returns per-sample loss [N].

    Target assignment is done with dense one-hot scatter instead of the
    reference's per-box C++ loops: each gt picks its best full-anchor-set
    match by width/height IoU; if that anchor is in anchor_mask the gt is
    assigned to its grid cell. Objectness negatives with best-gt IoU above
    ignore_thresh are excluded, matching the reference semantics.
    """
    x = jnp.asarray(x)
    gt_box = jnp.asarray(gt_box, jnp.float32)
    gt_label = jnp.asarray(gt_label, jnp.int32)
    n, c, h, w = x.shape
    an = len(anchor_mask)
    assert c == an * (5 + class_num)
    b = gt_box.shape[1]
    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    mask_idx = jnp.asarray(anchor_mask, jnp.int32)
    input_size = downsample_ratio * h

    pred = x.reshape(n, an, 5 + class_num, h, w)
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)

    valid = (gt_box[..., 2] > 0).astype(jnp.float32)          # [N, B]
    if gt_score is None:
        gt_score = valid
    else:
        gt_score = jnp.asarray(gt_score, jnp.float32) * valid

    # best anchor per gt over the FULL anchor set by wh-IoU at origin
    gwh = gt_box[..., 2:4] * input_size                        # [N,B,2]
    inter = (jnp.minimum(gwh[:, :, None, 0], all_anchors[None, None, :, 0])
             * jnp.minimum(gwh[:, :, None, 1], all_anchors[None, None, :, 1]))
    union = (gwh[..., 0:1] * gwh[..., 1:2]
             + all_anchors[None, None, :, 0] * all_anchors[None, None, :, 1]
             - inter)
    an_iou = inter / jnp.maximum(union, 1e-10)                 # [N,B,Atot]
    best = jnp.argmax(an_iou, axis=-1).astype(jnp.int32)       # [N,B]
    # position of best anchor inside anchor_mask, -1 if absent
    in_mask = (best[..., None] == mask_idx[None, None, :])     # [N,B,an]
    has_mask = in_mask.any(-1)
    mask_pos = jnp.argmax(in_mask, axis=-1).astype(jnp.int32)  # [N,B]
    assigned = valid * has_mask.astype(jnp.float32)            # [N,B]

    gi = jnp.clip((gt_box[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # scatter targets into [N, an, h, w] grids
    bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, b))
    sel = (bidx, mask_pos, gj, gi)
    wgt = assigned * gt_score                                   # [N,B]
    zeros = jnp.zeros((n, an, h, w), jnp.float32)

    tobj = zeros.at[sel].max(assigned)
    obj_weight = zeros.at[sel].max(wgt)
    tx = zeros.at[sel].set(jnp.where(assigned > 0,
                                     gt_box[..., 0] * w - gi, 0.0))
    ty = zeros.at[sel].set(jnp.where(assigned > 0,
                                     gt_box[..., 1] * h - gj, 0.0))
    anchor_wh = all_anchors[mask_idx]                           # [an,2]
    tw = zeros.at[sel].set(jnp.where(
        assigned > 0,
        jnp.log(jnp.maximum(gwh[..., 0], 1e-9)
                / anchor_wh[mask_pos][..., 0]), 0.0))
    th = zeros.at[sel].set(jnp.where(
        assigned > 0,
        jnp.log(jnp.maximum(gwh[..., 1], 1e-9)
                / anchor_wh[mask_pos][..., 1]), 0.0))
    # loss weight 2 - gw*gh (normalized): bigger weight for small boxes
    box_w = zeros.at[sel].set(jnp.where(
        assigned > 0,
        2.0 - gt_box[..., 2] * gt_box[..., 3], 0.0)) * obj_weight

    tcls = jnp.zeros((n, an, h, w, class_num), jnp.float32)
    smooth = 1.0 / max(class_num, 1) if (use_label_smooth
                                         and class_num > 1) else 0.0
    onehot = jax.nn.one_hot(gt_label, class_num)
    if smooth:
        onehot = onehot * (1.0 - smooth) + smooth * (1.0 / class_num)
    tcls = tcls.at[sel].set(onehot * assigned[..., None])

    # decode predicted boxes for the ignore mask
    grid_x = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
    px = (_sigmoid(pred[:, :, 0]) * alpha + beta + grid_x) / w
    py = (_sigmoid(pred[:, :, 1]) * alpha + beta + grid_y) / h
    pw = jnp.exp(jnp.clip(pred[:, :, 2], -10, 10)) \
        * anchor_wh[None, :, 0, None, None] / input_size
    ph = jnp.exp(jnp.clip(pred[:, :, 3], -10, 10)) \
        * anchor_wh[None, :, 1, None, None] / input_size
    pbox = jnp.stack([px, py, pw, ph], -1)                      # [N,an,h,w,4]
    iou = _box_iou_xywh(pbox[:, :, :, :, None, :],
                        gt_box[:, None, None, None, :, :])      # [N,an,h,w,B]
    best_iou = jnp.max(iou * valid[:, None, None, None, :], axis=-1)
    ignore = (best_iou > ignore_thresh).astype(jnp.float32) * (1.0 - tobj)

    loss_xy = box_w * (_bce(pred[:, :, 0], tx) + _bce(pred[:, :, 1], ty))
    loss_wh = box_w * (jnp.abs(pred[:, :, 2] - tw)
                       + jnp.abs(pred[:, :, 3] - th))
    loss_obj = obj_weight * _bce(pred[:, :, 4], tobj) \
        + (1.0 - tobj) * (1.0 - ignore) * _bce(pred[:, :, 4], tobj)
    loss_cls = obj_weight[..., None] * _bce(pred[:, :, 5:].transpose(
        0, 1, 3, 4, 2), tcls)

    per_sample = (loss_xy.sum((1, 2, 3)) + loss_wh.sum((1, 2, 3))
                  + loss_obj.sum((1, 2, 3)) + loss_cls.sum((1, 2, 3, 4)))
    return per_sample


# ---------------------------------------------------------------------------
# Deformable convolution (v1/v2)
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv (reference vision/ops.py:418; kernel
    operators/deformable_conv_op.h). mask=None → v1, else v2.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    mask: [N, dg*kh*kw, Ho, Wo]; weight: [Cout, Cin/groups, kh, kw].

    Implemented as bilinear gather of kh*kw shifted samples followed by a
    single grouped matmul (einsum → MXU); the gather indices come from the
    offset tensor so everything stays inside one XLA computation.
    """
    x = jnp.asarray(x)
    offset = jnp.asarray(offset)
    weight = jnp.asarray(weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    ho, wo = offset.shape[2], offset.shape[3]
    dg = deformable_groups
    k = kh * kw

    xp = jnp.pad(x, ((0, 0), (0, 0), (padding[0], padding[0]),
                     (padding[1], padding[1])))
    hp, wp = xp.shape[2], xp.shape[3]

    # base sampling positions p0 + pk, per output pixel and kernel point
    out_y = jnp.arange(ho, dtype=jnp.float32) * stride[0]
    out_x = jnp.arange(wo, dtype=jnp.float32) * stride[1]
    ker_y = jnp.arange(kh, dtype=jnp.float32) * dilation[0]
    ker_x = jnp.arange(kw, dtype=jnp.float32) * dilation[1]
    base_y = out_y[:, None] + ker_y[None, :]      # [ho, kh]
    base_x = out_x[:, None] + ker_x[None, :]      # [wo, kw]

    off = offset.reshape(n, dg, k, 2, ho, wo)
    off_y = off[:, :, :, 0]                       # [N, dg, k, ho, wo]
    off_x = off[:, :, :, 1]
    ky = jnp.repeat(jnp.arange(kh), kw)           # k → kernel row
    kx = jnp.tile(jnp.arange(kw), kh)
    sy = base_y[:, ky].T[None, None, :, :, None] + off_y  # [N,dg,k,ho,wo]
    sx = base_x[:, kx].T[None, None, :, None, :] + off_x

    y0 = jnp.floor(sy)
    x0 = jnp.floor(sx)
    wy1, wx1 = sy - y0, sx - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def gather(iy, ix):
        iyc = jnp.clip(iy.astype(jnp.int32), 0, hp - 1)
        ixc = jnp.clip(ix.astype(jnp.int32), 0, wp - 1)
        inb = ((iy >= 0) & (iy <= hp - 1) & (ix >= 0)
               & (ix <= wp - 1)).astype(x.dtype)
        # xp: [N, Cin, hp, wp] → samples [N, Cin, dg, k, ho, wo] with the
        # channel groups sharing their dg's indices
        cg = cin // dg
        xg = xp.reshape(n, dg, cg, hp, wp)
        flat = xg.reshape(n, dg, cg, hp * wp)
        idx = iyc * wp + ixc                      # [N, dg, k, ho, wo]
        took = jnp.take_along_axis(
            flat[:, :, :, None, :],
            idx.reshape(n, dg, 1, k, ho * wo).astype(jnp.int32),
            axis=-1)                               # [N, dg, cg, k, ho*wo]
        return took.reshape(n, dg, cg, k, ho, wo) * inb[:, :, None]

    val = (gather(y0, x0) * (wy0 * wx0)[:, :, None]
           + gather(y0, x0 + 1) * (wy0 * wx1)[:, :, None]
           + gather(y0 + 1, x0) * (wy1 * wx0)[:, :, None]
           + gather(y0 + 1, x0 + 1) * (wy1 * wx1)[:, :, None])

    if mask is not None:
        m = jnp.asarray(mask).reshape(n, dg, 1, k, ho, wo)
        val = val * m

    val = val.reshape(n, cin, k, ho, wo)
    # grouped contraction: [N, G, cin_g, k, ho, wo] x [G, cog, cin_g, k]
    cog = cout // groups
    vg = val.reshape(n, groups, cin // groups, k, ho, wo)
    wg = weight.reshape(groups, cog, cin_g, kh * kw)
    out = jnp.einsum("ngckhw,gock->ngohw", vg, wg,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, cout, ho, wo).astype(x.dtype)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(1, cout, 1, 1)
    return out


class DeformConv2D(Layer):
    """reference vision/ops.py:621 DeformConv2D (v1 when called without
    mask, v2 with)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels * ks[0] * ks[1] // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, ks[0], ks[1]),
            attr=weight_attr, initializer=I.Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                initializer=I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight.value,
            None if self.bias is None else self.bias.value,
            stride=self._stride, padding=self._padding,
            dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)


# ---------------------------------------------------------------------------
# Image IO
# ---------------------------------------------------------------------------
def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (reference vision/ops.py:810)."""
    with open(filename, "rb") as f:
        data = f.read()
    return jnp.asarray(np.frombuffer(data, dtype=np.uint8))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference vision/ops.py:855;
    the CUDA path uses nvjpeg — here PIL on host, a pure IO op)."""
    import io as _io

    from PIL import Image

    buf = np.asarray(x).tobytes()
    img = Image.open(_io.BytesIO(buf))
    if mode.lower() == "gray":
        img = img.convert("L")
    elif mode.lower() == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)


# ---------------------------------------------------------------------------
# Detection op suite (reference: paddle/fluid/operators/detection/*) —
# priors/anchors, box transforms, IoU/matching, NMS family, RoI pooling.
# Dense/grid ops are pure jax (jit-compatible, differentiable where the
# reference op is); data-dependent-output ops (NMS selection, bipartite
# match) run on host like the reference's CPU-only kernels and are
# eager-only.
# ---------------------------------------------------------------------------
def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              max_sizes=None, flip=False, clip=False, steps=(0.0, 0.0),
              offset=0.5, min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes per feature-map cell (reference
    detection/prior_box_op.h:53 kernel). Returns (boxes, variances), each
    (feat_h, feat_w, num_priors, 4), boxes normalized to [0,1] image
    coords."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_w = steps[0] or img_w / feat_w
    step_h = steps[1] or img_h / feat_h
    ars = _expand_aspect_ratios(aspect_ratios, flip)
    max_sizes = list(max_sizes or [])

    cx = (np.arange(feat_w) + offset) * step_w
    cy = (np.arange(feat_h) + offset) * step_h
    cx, cy = np.meshgrid(cx, cy)              # (H, W)

    halves = []  # (half_w, half_h) per prior, reference emission order
    for s, mn in enumerate(np.asarray(min_sizes, dtype="f8")):
        if min_max_aspect_ratios_order:
            halves.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = np.sqrt(mn * max_sizes[s]) / 2.0
                halves.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                halves.append((mn * np.sqrt(ar) / 2.0,
                               mn / np.sqrt(ar) / 2.0))
        else:
            for ar in ars:
                halves.append((mn * np.sqrt(ar) / 2.0,
                               mn / np.sqrt(ar) / 2.0))
            if max_sizes:
                m = np.sqrt(mn * max_sizes[s]) / 2.0
                halves.append((m, m))
    hw = np.asarray([h[0] for h in halves])   # (P,)
    hh = np.asarray([h[1] for h in halves])
    boxes = np.stack([
        (cx[..., None] - hw) / img_w, (cy[..., None] - hh) / img_h,
        (cx[..., None] + hw) / img_w, (cy[..., None] + hh) / img_h,
    ], axis=-1)                               # (H, W, P, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, dtype="f4"), boxes.shape)
    return jnp.asarray(boxes, jnp.float32), jnp.asarray(var)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Density prior boxes (reference detection/density_prior_box_op.h):
    each (density, fixed_size) pair tiles density^2 shifted centers."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_w = steps[0] or img_w / feat_w
    step_h = steps[1] or img_h / feat_h
    cx = (np.arange(feat_w) + offset) * step_w
    cy = (np.arange(feat_h) + offset) * step_h
    cx, cy = np.meshgrid(cx, cy)

    all_boxes = []
    for density, fs in zip(densities, fixed_sizes):
        for ratio in fixed_ratios:
            bw = fs * np.sqrt(ratio)
            bh = fs / np.sqrt(ratio)
            shift_w = step_w / density
            shift_h = step_h / density
            for di in range(density):
                for dj in range(density):
                    c_x = cx - step_w / 2.0 + shift_w / 2.0 + dj * shift_w
                    c_y = cy - step_h / 2.0 + shift_h / 2.0 + di * shift_h
                    all_boxes.append(np.stack([
                        (c_x - bw / 2.0) / img_w, (c_y - bh / 2.0) / img_h,
                        (c_x + bw / 2.0) / img_w, (c_y + bh / 2.0) / img_h,
                    ], axis=-1))
    boxes = np.stack(all_boxes, axis=2)       # (H, W, P, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, dtype="f4"), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return jnp.asarray(boxes, jnp.float32), jnp.asarray(var)


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors in absolute pixel coords (reference
    detection/anchor_generator_op.h)."""
    feat_h, feat_w = input.shape[2], input.shape[3]
    sw, sh = stride
    cx = (np.arange(feat_w) * sw) + offset * sw
    cy = (np.arange(feat_h) * sh) + offset * sh
    cx, cy = np.meshgrid(cx, cy)
    hws, hhs = [], []
    for ar in aspect_ratios:
        for size in anchor_sizes:
            area = sw * sh
            area_ratios = area / ar
            base_w = np.round(np.sqrt(area_ratios))
            base_h = np.round(base_w * ar)
            scale_w = size / sw
            scale_h = size / sh
            hws.append(0.5 * (scale_w * base_w - 1))
            hhs.append(0.5 * (scale_h * base_h - 1))
    hw = np.asarray(hws)
    hh = np.asarray(hhs)
    anchors = np.stack([cx[..., None] - hw, cy[..., None] - hh,
                        cx[..., None] + hw, cy[..., None] + hh], axis=-1)
    var = np.broadcast_to(np.asarray(variances, dtype="f4"), anchors.shape)
    return jnp.asarray(anchors, jnp.float32), jnp.asarray(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference
    detection/box_coder_op.h:41 EncodeCenterSize, :118 DecodeCenterSize).
    prior_box (M, 4); prior_box_var: None | (M, 4) array | list of 4.
    encode: target (N, 4) -> (N, M, 4); decode: target (N, M, 4) -> same.
    Pure jax, differentiable."""
    pb = jnp.asarray(prior_box, jnp.float32)
    norm = 1.0 if box_normalized else 0.0
    pw = pb[:, 2] - pb[:, 0] + (1.0 - norm)
    ph = pb[:, 3] - pb[:, 1] + (1.0 - norm)
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    var = None
    if prior_box_var is not None:
        var = jnp.asarray(prior_box_var, jnp.float32)

    t = jnp.asarray(target_box, jnp.float32)
    if code_type == "encode_center_size":
        tw = t[:, 2] - t[:, 0] + (1.0 - norm)
        th = t[:, 3] - t[:, 1] + (1.0 - norm)
        tcx = (t[:, 0] + t[:, 2]) / 2
        tcy = (t[:, 1] + t[:, 3]) / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)      # (N, M, 4)
        if var is not None:
            out = out / (var[None, :, :] if var.ndim == 2
                         else var.reshape(1, 1, 4))
        return out
    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")
    # decode: t is (N, M, 4); priors broadcast along `axis`
    if var is not None and var.ndim == 2:
        var = var[None, :, :] if axis == 0 else var[:, None, :]
    elif var is not None:
        var = var.reshape(1, 1, 4)
    if axis == 0:
        bpw, bph, bpcx, bpcy = (pw[None, :], ph[None, :],
                                pcx[None, :], pcy[None, :])
    else:
        bpw, bph, bpcx, bpcy = (pw[:, None], ph[:, None],
                                pcx[:, None], pcy[:, None])
    tv = t * var if var is not None else t
    w = jnp.exp(tv[..., 2]) * bpw
    h = jnp.exp(tv[..., 3]) * bph
    cx = tv[..., 0] * bpw + bpcx
    cy = tv[..., 1] * bph + bpcy
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - (1.0 - norm),
                      cy + h / 2 - (1.0 - norm)], axis=-1)


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference detection/box_clip_op.h;
    im_info = [h, w, scale]). Batched: im_info (N, 3) clips
    input (N, ..., 4) per image; a single [h, w, scale] clips all."""
    b = jnp.asarray(input, jnp.float32)
    info = jnp.asarray(im_info, jnp.float32)
    if info.ndim > 1:  # per-image bounds broadcast over the box dims
        extra = b.ndim - 2
        info = info.reshape((info.shape[0],) + (1,) * extra + (3,))
    h = info[..., 0] / info[..., 2] - 1.0
    w = info[..., 1] / info[..., 2] - 1.0
    return jnp.stack([
        jnp.clip(b[..., 0], 0.0, w), jnp.clip(b[..., 1], 0.0, h),
        jnp.clip(b[..., 2], 0.0, w), jnp.clip(b[..., 3], 0.0, h)],
        axis=-1)


def _pairwise_iou(x, y, normalized=True):
    eps = 0.0 if normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + eps) * (x[:, 3] - x[:, 1] + eps)
    area_y = (y[:, 2] - y[:, 0] + eps) * (y[:, 3] - y[:, 1] + eps)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt + eps, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _pairwise_iou_np(x, normalized=True):
    """Self-IoU on host (the NMS loops are host-side: no device bounce)."""
    eps = 0.0 if normalized else 1.0
    area = (x[:, 2] - x[:, 0] + eps) * (x[:, 3] - x[:, 1] + eps)
    lt = np.maximum(x[:, None, :2], x[None, :, :2])
    rb = np.minimum(x[:, None, 2:], x[None, :, 2:])
    wh = np.maximum(rb - lt + eps, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(union > 0, inter / union, 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU (N,4) x (M,4) -> (N,M) (reference
    detection/iou_similarity_op.h). Pure jax."""
    return _pairwise_iou(jnp.asarray(x, jnp.float32),
                         jnp.asarray(y, jnp.float32), box_normalized)


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference detection/bipartite_match_op.cc):
    repeatedly take the globally largest entry, retiring its row and
    column. Returns (match_indices (M,), match_dist (M,)) over columns.
    Host-side (data-dependent control flow), eager-only like the
    reference's CPU kernel."""
    d = np.array(dist_matrix, dtype=np.float64, copy=True)
    n, m = d.shape
    match_idx = np.full((m,), -1, dtype=np.int64)
    match_dist = np.zeros((m,), dtype=np.float32)
    live = d.copy()
    for _ in range(min(n, m)):
        i, j = np.unravel_index(np.argmax(live), live.shape)
        if live[i, j] <= 0:
            break
        match_idx[j] = i
        match_dist[j] = d[i, j]
        live[i, :] = -1.0
        live[:, j] = -1.0
    if match_type == "per_prediction":
        thr = dist_threshold if dist_threshold is not None else 0.5
        for j in range(m):
            if match_idx[j] == -1:
                i = int(np.argmax(d[:, j]))
                if d[i, j] >= thr:
                    match_idx[j] = i
                    match_dist[j] = d[i, j]
    return jnp.asarray(match_idx), jnp.asarray(match_dist)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, normalized=True, nms_eta=1.0,
        name=None):
    """Hard NMS returning kept indices sorted by score (reference
    paddle.vision.ops.nms / detection NMS kernels). Host-side
    (data-dependent output length), eager-only. ``normalized=False``
    uses pixel-coordinate IoU (+1 extents); ``nms_eta < 1`` shrinks the
    threshold adaptively after each kept box (reference NMSFast)."""
    b = np.asarray(boxes, dtype=np.float64)
    n = b.shape[0]
    s = (np.asarray(scores, dtype=np.float64) if scores is not None
         else np.arange(n, 0, -1, dtype=np.float64))
    if category_idxs is not None:
        # per-category NMS: offset boxes so categories never overlap
        cat = np.asarray(category_idxs)
        off = (b.max() + 1.0) * cat.astype(np.float64)
        b = b + off[:, None]
    order = np.argsort(-s)
    keep = []
    iou = _pairwise_iou_np(b, normalized=normalized)
    suppressed = np.zeros(n, dtype=bool)
    thr = float(iou_threshold)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > thr
        suppressed[i] = True
        if nms_eta < 1.0 and thr > 0.5:
            thr *= nms_eta
    keep = np.asarray(keep, dtype=np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return jnp.asarray(keep)


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """Per-class NMS + cross-class top-k (reference
    detection/multiclass_nms_op.cc). bboxes (N, M, 4), scores (N, C, M).
    Returns list per image of (label, score, x1, y1, x2, y2) arrays —
    host-side, eager-only (LoD output in the reference)."""
    bboxes = np.asarray(bboxes)
    scores = np.asarray(scores)
    outs = []
    for n in range(bboxes.shape[0]):
        dets = []
        for c in range(scores.shape[1]):
            if c == background_label:
                continue
            s = scores[n, c]
            mask = s > score_threshold
            if not mask.any():
                continue
            idx = np.nonzero(mask)[0]
            idx = idx[np.argsort(-s[idx])][:nms_top_k]
            keep = np.asarray(nms(bboxes[n, idx], nms_threshold,
                                  scores=s[idx], normalized=normalized,
                                  nms_eta=nms_eta))
            for i in np.asarray(idx)[keep]:
                dets.append([c, s[i], *bboxes[n, i]])
        if dets:
            dets = np.asarray(dets, dtype=np.float32)
            dets = dets[np.argsort(-dets[:, 1])][:keep_top_k]
        else:
            dets = np.zeros((0, 6), dtype=np.float32)
        outs.append(jnp.asarray(dets))
    return outs


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               name=None):
    """Matrix NMS (reference detection/matrix_nms_op.cc): parallel soft
    suppression by decayed scores — no sequential suppression loop. Pure
    numpy per image (selection still data-dependent), decay math matches
    the reference kernel."""
    bboxes = np.asarray(bboxes)
    scores = np.asarray(scores)
    outs = []
    for n in range(bboxes.shape[0]):
        dets = []
        for c in range(scores.shape[1]):
            if c == background_label:
                continue
            s = scores[n, c]
            mask = s > score_threshold
            if not mask.any():
                continue
            idx = np.nonzero(mask)[0]
            idx = idx[np.argsort(-s[idx])][:nms_top_k]
            sel = bboxes[n, idx]
            ss = s[idx]
            iou = _pairwise_iou_np(sel.astype(np.float64), normalized)
            iou = np.triu(iou, k=1)             # iou[i, j] for i < j only
            # comp[i] = max IoU of box i with any higher-scored box —
            # the reference's compensation term (matrix_nms_op.cc): decay
            # for box j = min over i<j of f(iou_ij) / f(comp_i)
            comp = iou.max(axis=0)
            k = iou.shape[0]
            excl = np.tril(np.ones((k, k), dtype=bool))  # i >= j: no-op
            if use_gaussian:
                # reference matrix_nms_op.cc:87 decay_score<T, true>:
                # exp((max_iou^2 - iou^2) * sigma)
                ratio = np.exp((comp[:, None] ** 2 - iou ** 2)
                               * gaussian_sigma)
            else:
                ratio = (1.0 - iou) / np.maximum(1.0 - comp[:, None],
                                                 1e-10)
            ratio = np.where(excl, 1.0, ratio)
            decay = ratio.min(axis=0)
            decayed = ss * decay
            keep = decayed > post_threshold
            for i, sc in zip(np.asarray(idx)[keep], decayed[keep]):
                dets.append([c, sc, *bboxes[n, i]])
        if dets:
            dets = np.asarray(dets, dtype=np.float32)
            dets = dets[np.argsort(-dets[:, 1])][:keep_top_k]
        else:
            dets = np.zeros((0, 6), dtype=np.float32)
        outs.append(jnp.asarray(dets))
    return outs


def _bilinear_gather(feat, y, x):
    """feat (C, H, W); y/x arbitrary same-shaped sample coords."""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly = jnp.clip(y - y0, 0.0, 1.0)
    lx = jnp.clip(x - x0, 0.0, 1.0)
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
            v10 * ly * (1 - lx) + v11 * ly * lx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoI Align (reference operators/roi_align_op.*): bilinear sampling on
    a regular in-bin grid, averaged. Pure jax, differentiable, static
    shapes (sampling_ratio <= 0 uses 2 samples/bin — a static stand-in for
    the reference's per-roi adaptive count)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)
    img_idx = jnp.asarray(np.repeat(np.arange(len(boxes_num)), boxes_num),
                          jnp.int32)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw
    # sample grid: (R, ph, sr) y-coords and (R, pw, sr) x-coords
    iy = (jnp.arange(sr) + 0.5) / sr
    ys = (y1[:, None, None] + (jnp.arange(ph)[None, :, None] +
                               iy[None, None, :]) * bin_h[:, None, None])
    xs = (x1[:, None, None] + (jnp.arange(pw)[None, :, None] +
                               iy[None, None, :]) * bin_w[:, None, None])

    def one_roi(feat, ys_r, xs_r):
        yy = jnp.broadcast_to(ys_r[:, None, :, None], (ph, pw, sr, sr))
        xx = jnp.broadcast_to(xs_r[None, :, None, :], (ph, pw, sr, sr))
        vals = _bilinear_gather(feat, yy, xx)       # (C, ph, pw, sr, sr)
        return vals.mean(axis=(-1, -2))             # (C, ph, pw)

    feats = x[img_idx]                              # (R, C, H, W)
    return jax.vmap(one_roi)(feats, ys, xs)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoI max pooling (reference operators/roi_pool_op.*): exact integer
    bins via separable masked max (max over w then h). Pure jax,
    differentiable through the max."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    H, W = x.shape[2], x.shape[3]
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)
    img_idx = jnp.asarray(np.repeat(np.arange(len(boxes_num)), boxes_num),
                          jnp.int32)
    x1 = jnp.round(boxes[:, 0] * spatial_scale)
    y1 = jnp.round(boxes[:, 1] * spatial_scale)
    x2 = jnp.round(boxes[:, 2] * spatial_scale)
    y2 = jnp.round(boxes[:, 3] * spatial_scale)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = rh / ph
    bin_w = rw / pw

    hs = jnp.arange(H, dtype=jnp.float32)
    ws = jnp.arange(W, dtype=jnp.float32)
    # bin [i] covers [floor(y1 + i*bin_h), ceil(y1 + (i+1)*bin_h))
    i = jnp.arange(ph, dtype=jnp.float32)
    j = jnp.arange(pw, dtype=jnp.float32)
    hstart = jnp.clip(jnp.floor(y1[:, None] + i[None, :] * bin_h[:, None]),
                      0, H)
    hend = jnp.clip(jnp.ceil(y1[:, None] + (i[None, :] + 1) *
                             bin_h[:, None]), 0, H)
    wstart = jnp.clip(jnp.floor(x1[:, None] + j[None, :] * bin_w[:, None]),
                      0, W)
    wend = jnp.clip(jnp.ceil(x1[:, None] + (j[None, :] + 1) *
                             bin_w[:, None]), 0, W)
    rowm = ((hs[None, None, :] >= hstart[..., None]) &
            (hs[None, None, :] < hend[..., None]))    # (R, ph, H)
    colm = ((ws[None, None, :] >= wstart[..., None]) &
            (ws[None, None, :] < wend[..., None]))    # (R, pw, W)
    feats = x[img_idx]                                # (R, C, H, W)
    neg = jnp.finfo(x.dtype).min

    # max over w (masked by colm), then over h (masked by rowm) — max is
    # separable, so no (R, ph, pw, H, W) tensor is ever materialized
    def one_roi(feat, rm, cm):
        t = jnp.where(cm[None, None, :, :], feat[:, :, None, :], neg)
        t = t.max(axis=-1)                            # (C, H, pw)
        t2 = jnp.where(rm[None, :, :, None], t[:, None, :, :], neg)
        out = t2.max(axis=2)                          # (C, ph, pw)
        empty = (~rm.any(-1))[None, :, None] | (~cm.any(-1))[None, None, :]
        return jnp.where(empty, 0.0, out)

    return jax.vmap(one_roi)(feats, rowm, colm)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (reference
    operators/psroi_pool_op.*): input channels C = out_c * ph * pw; bin
    (i, j) averages channel group (i*pw + j). Pure jax."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    C, H, W = x.shape[1], x.shape[2], x.shape[3]
    assert C % (ph * pw) == 0, "channels must be out_c * ph * pw"
    out_c = C // (ph * pw)
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)
    img_idx = jnp.asarray(np.repeat(np.arange(len(boxes_num)), boxes_num),
                          jnp.int32)
    x1 = jnp.round(boxes[:, 0]) * spatial_scale
    y1 = jnp.round(boxes[:, 1]) * spatial_scale
    x2 = jnp.round(boxes[:, 2] + 1.0) * spatial_scale
    y2 = jnp.round(boxes[:, 3] + 1.0) * spatial_scale
    rh = jnp.maximum(y2 - y1, 0.1)
    rw = jnp.maximum(x2 - x1, 0.1)
    bin_h = rh / ph
    bin_w = rw / pw
    hs = jnp.arange(H, dtype=jnp.float32)
    ws = jnp.arange(W, dtype=jnp.float32)
    i = jnp.arange(ph, dtype=jnp.float32)
    j = jnp.arange(pw, dtype=jnp.float32)
    hstart = jnp.clip(jnp.floor(y1[:, None] + i[None, :] * bin_h[:, None]),
                      0, H)
    hend = jnp.clip(jnp.ceil(y1[:, None] + (i[None, :] + 1) *
                             bin_h[:, None]), 0, H)
    wstart = jnp.clip(jnp.floor(x1[:, None] + j[None, :] * bin_w[:, None]),
                      0, W)
    wend = jnp.clip(jnp.ceil(x1[:, None] + (j[None, :] + 1) *
                             bin_w[:, None]), 0, W)
    rowm = ((hs[None, None, :] >= hstart[..., None]) &
            (hs[None, None, :] < hend[..., None])).astype(x.dtype)
    colm = ((ws[None, None, :] >= wstart[..., None]) &
            (ws[None, None, :] < wend[..., None])).astype(x.dtype)
    feats = x[img_idx].reshape(-1, out_c, ph, pw, H, W)  # (R, oc, ph, pw, H, W)

    def one_roi(feat, rm, cm):
        # feat (oc, ph, pw, H, W); average over each bin's h/w window
        t = jnp.einsum("opqhw,qw->opqh", feat, cm)     # sum over w per bin-col
        t = jnp.einsum("opqh,ph->opq", t, rm)          # sum over h per bin-row
        cnt = jnp.einsum("ph,qw->pq", rm, cm)
        return jnp.where(cnt[None] > 0, t / jnp.maximum(cnt[None], 1.0), 0.0)

    return jax.vmap(one_roi)(feats, rowm, colm)


def polygon_box_transform(input, name=None):
    """EAST geometry-map offsets -> absolute quad coords (reference
    detection/polygon_box_transform_op.cc: even channels use 4*w - v, odd
    use 4*h - v)."""
    x = jnp.asarray(input)
    n, c, h, w = x.shape
    jj = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    ii = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    even = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    return jnp.where(even, jj - x, ii - x)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    """RPN proposal generation (reference
    detection/generate_proposals_v2_op.cc): decode anchors with deltas,
    clip, filter small, NMS. Host-side selection, eager-only."""
    scores = np.asarray(scores)        # (N, A, H, W)
    deltas = np.asarray(bbox_deltas)   # (N, A*4, H, W)
    img_size = np.asarray(img_size)    # (N, 2) [h, w]
    anc = np.asarray(anchors).reshape(-1, 4)
    var = np.asarray(variances).reshape(-1, 4)
    # reference bbox_util.h:197 FilterBoxes clamps the size floor to 1px
    min_size = max(min_size, 1.0)
    N = scores.shape[0]
    rois, roi_scores, rois_num = [], [], []
    for n in range(N):
        s = scores[n].transpose(1, 2, 0).reshape(-1)          # (H*W*A,)
        d = deltas[n].reshape(scores.shape[1], 4,
                              scores.shape[2], scores.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        a = anc[order]
        v = var[order]
        # decode (variance-scaled center-size)
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        wd = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        hd = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - wd / 2, cy - hd / 2,
                          cx + wd / 2 - 1, cy + hd / 2 - 1], axis=1)
        ih, iw = img_size[n, 0], img_size[n, 1]
        boxes = np.stack([
            np.clip(boxes[:, 0], 0, iw - 1), np.clip(boxes[:, 1], 0, ih - 1),
            np.clip(boxes[:, 2], 0, iw - 1), np.clip(boxes[:, 3], 0, ih - 1),
        ], axis=1)
        keep_sz = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size) &
                   (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        boxes, s = boxes[keep_sz], s[keep_sz]
        if boxes.shape[0]:
            keep = np.asarray(nms(boxes, nms_thresh,
                                  scores=s))[:post_nms_top_n]
            boxes, s = boxes[keep], s[keep]
        rois.append(jnp.asarray(boxes, jnp.float32))
        roi_scores.append(jnp.asarray(s, jnp.float32))
        rois_num.append(boxes.shape[0])
    out_rois = (jnp.concatenate(rois, 0) if rois else
                jnp.zeros((0, 4), jnp.float32))
    scores_out = (jnp.concatenate(roi_scores, 0) if roi_scores else
                  jnp.zeros((0,), jnp.float32))
    if return_rois_num:
        return out_rois, scores_out, jnp.asarray(rois_num, jnp.int32)
    return out_rois, scores_out
