"""Pipeline-parallel engine (reference:
fleet/meta_parallel/pipeline_parallel.py:114 train_batch — micro-batch
forward :156 / backward :199 loops with p2p send/recv
(pp_utils/p2p_communication.py:84,:93); static 1F1B in
framework/section_worker.cc:139-183).

TPU-native schedule: the whole pipeline is ONE SPMD program under shard_map
over the "pipe" mesh axis. Activations move between stages with
lax.ppermute; the schedule is a lax.scan over M + S - 1 ticks (GPipe fill +
steady state). The *backward* pipeline is not hand-written: jax AD
differentiates through the scan, transposing every ppermute into the
reverse-direction hop — producing exactly the reversed communication pattern
that pipeline_parallel.py:199 implements manually. Per-microbatch activation
memory is bounded with jax.checkpoint (remat) over each stage application,
which is how 1F1B's memory advantage is recovered on TPU (remat trades the
stashed activations for recompute, reference C54 recompute).

Stage dispatch inside the SPMD program is a lax.switch on the stage id —
first stage consumes the (replicated) token microbatch, the last computes
the loss; middle stages are pure activation → activation maps.
"""
from __future__ import annotations

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax import lax

from ...jit.functionalization import functional_call, state_of
from ...nn.layer import Layer

PIPE_AXIS = "pipe"


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        from .parallel_layers.pp_layers import PipelineLayer
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel requires a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.accumulate_steps = 1
        if strategy is not None:
            self.accumulate_steps = int(
                strategy.pipeline_configs.get("accumulate_steps", 1))
        self._compiled = None

    # -- single-device semantics (debug/eval) ------------------------------
    def forward(self, x):
        return self._layers(x)

    # -- the SPMD pipelined loss -------------------------------------------
    def build_pipeline_loss_fn(self, loss_fn, micro_batches: int):
        """Return pure_loss(params, buffers, rng, inputs, labels) that runs
        the GPipe schedule inside an active shard_map over the pipe axis.

        inputs/labels are the FULL batch (replicated over pipe); they are
        re-split into `micro_batches` microbatches here (reference
        pipeline_parallel.py _load_micro_batch).
        """
        layers = self._layers
        S = self.num_stages
        M = micro_batches
        segment = layers.segment

        def stage_forward(stage_id, params, buffers, h, key):
            """Apply the layers of `stage_id` functionally."""
            lo, hi = segment[stage_id], segment[stage_id + 1]
            out = h
            for i in range(lo, hi):
                sub = layers.runs[i]
                sub_prefix = f"runs.{i}"
                sub_params = {k[len(sub_prefix) + 1:]: v for k, v in params.items()
                              if k.startswith(sub_prefix + ".")}
                sub_bufs = {k[len(sub_prefix) + 1:]: v for k, v in buffers.items()
                            if k.startswith(sub_prefix + ".")}
                (out), _ = functional_call(sub, sub_params, sub_bufs, out,
                                           rng=jax.random.fold_in(key, i))
            return out

        def pure_loss(params, buffers, key, inputs, labels):
            sid = lax.axis_index(PIPE_AXIS)
            mb = inputs.shape[0] // M
            micro_in = inputs.reshape((M, mb) + inputs.shape[1:])
            micro_lb = labels.reshape((M, mb) + labels.shape[1:])

            # probe the carry shape: trace stage0 on microbatch 0
            h_shape = jax.eval_shape(
                lambda: stage_forward(0, params, buffers,
                                      micro_in[0], key)).shape
            h_dtype = jax.eval_shape(
                lambda: stage_forward(0, params, buffers,
                                      micro_in[0], key)).dtype

            def apply_stage(s, h_in, m, key):
                """Branch for stage s; every branch returns (h, loss)."""
                def branch(h):
                    x0 = micro_in[m] if s == 0 else h
                    out = stage_forward(s, params, buffers, x0, key)
                    if s == S - 1:
                        l = loss_fn(out, micro_lb[m])
                        return out.astype(h_dtype) if out.shape == h_shape \
                            else jnp.zeros(h_shape, h_dtype), l
                    return out, jnp.zeros((), jnp.float32)
                return branch

            def tick(carry, t):
                h_recv, loss_acc = carry
                m = jnp.clip(t - sid, 0, M - 1)
                valid = (t - sid >= 0) & (t - sid < M)
                k_t = jax.random.fold_in(key, t)
                branches = [_remat_branch(apply_stage(s, h_recv, m, k_t))
                            for s in range(S)]
                h_out, l = lax.switch(sid, branches, h_recv)
                l = jnp.where(valid, l, 0.0)
                loss_acc = loss_acc + l
                h_next = lax.ppermute(
                    h_out, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
                return (h_next, loss_acc), None

            h0 = jnp.zeros(h_shape, h_dtype)
            (h_last, loss_acc), _ = lax.scan(
                tick, (h0, jnp.zeros((), jnp.float32)), jnp.arange(M + S - 1))
            # only the last stage accumulated loss; broadcast it
            from .parallel_layers.mp_layers import \
                reduce_from_parallel_region
            total = reduce_from_parallel_region(loss_acc, axis=PIPE_AXIS)
            return total / M

        def _remat_branch(branch):
            return jax.checkpoint(branch)

        return pure_loss

    # passthrough
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
