"""Predicted-vs-measured calibration: close the loop between the static
cost models and runtime reality (ISSUE 18).

The stack predicts time everywhere — ``analysis/cost.overlap_summary``'s
step makespan, the sharding pass's resharding wire seconds, the serving
admission model's modeled wait, the tuner DB's ``mean_us`` — but until
this module nothing ever checked a prediction against what actually ran,
so ``mesh.LINK_BANDWIDTHS`` and ``peak_flops_per_sec()`` were guesses
and every planner decision built on them inherited unbounded error.
Two layers fix that:

**Pair registry** — instrumentation sites call ``record(key, predicted,
measured)`` with a stable key per model:

=====================  ====================================================
key                    prediction vs measurement
=====================  ====================================================
``step_time``          ``cost.overlap_summary`` makespan of the staged
                       step vs the measured ``train_step`` wall time
                       (engine._record_step_telemetry)
``serving_queue_wait`` admission's modeled wait (x admission_safety) vs
                       the request's measured admission->first-dispatch
                       wait (serving._dispatch)
``collective_<link>``  ring wire model (bytes / bandwidth + latency) vs
                       a measured collective exchange
                       (bench_collectives --suite exchange|calibrate)
``tuner:<kernel>``     tuning-DB ``mean_us`` vs a fresh device timing of
                       the same entry (ops.pallas.tuner.tune)
``planner_step_time``  ``auto.plan_search`` winner's predicted step time
                       vs the measured step time of running that chosen
                       config (tools/bench_plan.py, bench.py planner
                       block) — closes the loop on the planner itself
=====================  ====================================================

Every record exports ``calibration_drift_ratio{key}`` (= measured /
predicted) and ``calibration_samples_total{key}`` when telemetry is
enabled; the pairs themselves are module-owned accounting (like
``InferenceServer.counts``) so benches can read :func:`summary` without
a telemetry scope. An SLO-style drift rule latches per key: once at
least ``min_samples`` pairs exist and ``|log(measured/predicted)|``
exceeds ``drift_log_bound`` (default ln 4 — off by more than 4x either
way), it fires ONE reason-tagged flight-recorder dump
(``flight_calibration_drift_*.json``) and counts
``calibration_drift_breaches_total{key}``; the latch re-arms only after
drift recovers to half the bound in log space (slo.py's hysteresis).

**Fitting pass** — :func:`fit` regresses measured collective time
against the ring-cost wire model (``t = latency + bytes / bandwidth``,
least squares per link class) and measured step time against the staged
FLOPs (effective ``peak_flops_per_sec`` = median flops/second), then
persists the corrected constants to a ``calibration_db.json`` overlay
following the tuner-DB conventions exactly: shipped seed next to this
module + user overlay (``PADDLE_TPU_CALIBRATION_DB`` or
``~/.cache/paddle_tpu/calibration_db.json``), overlay wins per device
kind, atomic save, corrupt -> empty with one warning. Consumers pull
the constants at load through two choke points — ``mesh.link_bandwidth``
/ ``mesh.link_latency`` and ``telemetry.peak_flops_per_sec()`` — so
``cost.overlap_summary``, ``analysis/sharding`` pricing,
``auto.resharding_cost()`` and the serving admission model (seeded
EWMA, see ``InferenceServer``) all price time with measured constants.
Precedence everywhere: explicit env override > calibration DB > the
shipped defaults.

Run the fitting sweep with ``python tools/bench_collectives.py --suite
calibrate`` (writes the overlay); delete the overlay file to fall back
to the shipped constants.
"""
from __future__ import annotations

import json
import math
import os
import threading
import warnings
from typing import Dict, List, Optional, Tuple

from .metrics import StreamingQuantile

__all__ = [
    "record", "drift", "summary", "pair", "reset",
    "CalibrationRegistry", "CalibrationDB",
    "default_db_path", "overlay_db_path", "get_db", "clear_cache",
    "constants", "device_kind", "GENERIC_DEVICE",
    "link_bandwidth_override", "link_latency_override",
    "peak_flops_override", "serving_rates",
    "fit", "fit_link",
    "DRIFT_LOG_BOUND", "MIN_SAMPLES_FOR_BREACH",
]

GENERIC_DEVICE = "any"   # device-agnostic fallback entry (tuner convention)

_VERSION = 1

# |log(measured/predicted)| above this fires the drift rule: ln(4) means
# the model is off by more than 4x in either direction.
DRIFT_LOG_BOUND = math.log(4.0)
# a single noisy pair must not dump the flight ring
MIN_SAMPLES_FOR_BREACH = 5


# ---------------------------------------------------------------------------
# pair registry
# ---------------------------------------------------------------------------

class _KeyState:
    __slots__ = ("n", "predicted", "measured", "log_drifts", "latched",
                 "breaches")

    def __init__(self):
        self.n = 0
        self.predicted: Optional[float] = None   # most recent pair
        self.measured: Optional[float] = None
        self.log_drifts = StreamingQuantile(maxlen=256)
        self.latched = False                     # breach fired, not recovered
        self.breaches = 0


class CalibrationRegistry:
    """(prediction, measurement) pairs per stable key, with the latched
    drift rule. One module-global instance backs :func:`record`."""

    def __init__(self, drift_log_bound: float = DRIFT_LOG_BOUND,
                 min_samples: int = MIN_SAMPLES_FOR_BREACH):
        self._lock = threading.Lock()
        self._keys: Dict[str, _KeyState] = {}
        self.drift_log_bound = float(drift_log_bound)
        self.min_samples = int(min_samples)

    def record(self, key: str, predicted: float, measured: float,
               step: Optional[int] = None) -> Optional[float]:
        """Record one pair; returns the drift ratio measured/predicted
        (None when either side is non-positive — a ratio needs two
        positive quantities, and a cold-start model that predicted 0 has
        nothing to calibrate)."""
        try:
            predicted = float(predicted)
            measured = float(measured)
        except (TypeError, ValueError):
            return None
        if predicted <= 0.0 or measured <= 0.0:
            return None
        ratio = measured / predicted
        log_drift = math.log(ratio)
        breach = False
        with self._lock:
            st = self._keys.setdefault(key, _KeyState())
            st.n += 1
            st.predicted, st.measured = predicted, measured
            st.log_drifts.add(log_drift)
            if st.n >= self.min_samples and \
                    abs(log_drift) > self.drift_log_bound:
                if not st.latched:
                    st.latched = True
                    st.breaches += 1
                    breach = True
            elif abs(log_drift) <= self.drift_log_bound / 2.0:
                # hysteresis (slo.py's latch): re-arm only once drift
                # recovers to half the bound in log space
                st.latched = False
        from paddle_tpu import telemetry
        if telemetry.enabled():
            telemetry.gauge(
                "calibration_drift_ratio",
                "measured / predicted per calibration key (1.0 = the "
                "cost model is exact)").set(ratio, key=key)
            telemetry.counter(
                "calibration_samples_total",
                "(prediction, measurement) pairs recorded").inc(key=key)
            if breach:
                telemetry.counter(
                    "calibration_drift_breaches_total",
                    "latched |log drift| > bound events per key"
                ).inc(key=key)
        if breach:
            from . import flight
            flight.dump("calibration_drift", step=step, extra={
                "key": key, "predicted": predicted, "measured": measured,
                "drift": ratio, "log_drift": log_drift,
                "bound": self.drift_log_bound})
        return ratio

    def drift(self, key: str) -> Optional[float]:
        """Most recent drift ratio for ``key`` (None before any pair)."""
        with self._lock:
            st = self._keys.get(key)
            if st is None or not st.predicted or not st.measured:
                return None
            return st.measured / st.predicted

    def pair(self, key: str) -> Optional[dict]:
        """The bench-JSON ``{predicted, measured, drift}`` block for one
        key (None before any pair) — what every bench's one-line JSON
        embeds under ``calibration`` since schema_version 2."""
        with self._lock:
            st = self._keys.get(key)
            if st is None or st.predicted is None:
                return None
            return {"key": key, "predicted": st.predicted,
                    "measured": st.measured,
                    "drift": st.measured / st.predicted, "n": st.n}

    def summary(self) -> Dict[str, dict]:
        """Per-key drift summary (the streaming quantiles come from the
        shared ``metrics.StreamingQuantile``)."""
        out = {}
        with self._lock:
            for key, st in self._keys.items():
                out[key] = {
                    "n": st.n,
                    "predicted": st.predicted,
                    "measured": st.measured,
                    "drift": (st.measured / st.predicted
                              if st.predicted else None),
                    "log_drift_p50": st.log_drifts.median(),
                    "log_drift_p90": st.log_drifts.quantile(0.9),
                    "breaches": st.breaches,
                    "latched": st.latched,
                }
        return out

    def reset(self):
        with self._lock:
            self._keys.clear()


_registry = CalibrationRegistry()


def record(key: str, predicted: float, measured: float,
           step: Optional[int] = None) -> Optional[float]:
    return _registry.record(key, predicted, measured, step=step)


def drift(key: str) -> Optional[float]:
    return _registry.drift(key)


def pair(key: str) -> Optional[dict]:
    return _registry.pair(key)


def summary() -> Dict[str, dict]:
    return _registry.summary()


def reset():
    """Drop every recorded pair and latch (tests / fresh runs)."""
    _registry.reset()


# ---------------------------------------------------------------------------
# calibration DB (tuner-DB conventions: seed + overlay, atomic, fail-soft)
# ---------------------------------------------------------------------------

def default_db_path() -> str:
    """The in-repo seed DB shipped next to this module."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "calibration_db.json")


def overlay_db_path() -> str:
    """User-writable overlay: ``PADDLE_TPU_CALIBRATION_DB`` or a
    cache-dir default. ``fit()`` writes here so the seed stays pristine."""
    env = os.environ.get("PADDLE_TPU_CALIBRATION_DB")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "calibration_db.json")


def device_kind() -> str:
    """Normalized accelerator name keying the DB — the tuner's, so one
    notion of device identity serves both databases."""
    from ..ops.pallas.tuner import device_kind as _dk
    return _dk()


class CalibrationDB:
    """A {device_kind: entry} map with JSON round-trip. An entry is::

        {"links": {"ici": {"bandwidth_bps": 9.0e10, "latency_s": 2e-6,
                           "residual_rms_s": ..., "n": 4},
                   "dcn": {...}},
         "peak_flops_per_sec": 1.1e10,
         "serving": {"rows_per_s": 180.0, "batch_s": 0.05},
         "fitted": {"n_collective": 4, "n_compute": 3, "n_serving": 0}}

    Every field is optional — a partial fit (say, collectives only)
    overlays just what it measured and the consumers fall back to the
    shipped defaults for the rest.
    """

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: Optional[str] = None):
        self.entries: Dict[str, dict] = dict(entries or {})
        self.path = path

    # -- io -----------------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "CalibrationDB":
        """Missing or corrupt files yield an EMPTY db (warn once on
        corruption) — a broken overlay must never take down pricing."""
        if not os.path.exists(path):
            return cls(path=path)
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict) or \
                    not isinstance(raw.get("entries", {}), dict):
                raise ValueError("not a calibration DB object")
            return cls(raw.get("entries", {}), path=path)
        except (OSError, ValueError) as e:
            warnings.warn(f"calibration DB {path!r} unreadable ({e}); "
                          "treating as empty", stacklevel=2)
            return cls(path=path)

    def save(self, path: Optional[str] = None):
        path = path or self.path
        if not path:
            raise ValueError("CalibrationDB.save: no path")
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": _VERSION, "entries": self.entries}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)

    # -- access -------------------------------------------------------------
    def lookup(self, device: str) -> Optional[dict]:
        return self.entries.get(device)

    def put(self, device: str, entry: dict):
        self.entries[device] = entry

    def merged_over(self, base: "CalibrationDB") -> "CalibrationDB":
        """self (overlay) wins per device over ``base``."""
        merged = dict(base.entries)
        merged.update(self.entries)
        return CalibrationDB(merged)

    def __len__(self):
        return len(self.entries)


_db_cache: Dict[str, object] = {}


def get_db(refresh: bool = False) -> CalibrationDB:
    """The merged (seed + overlay) DB, cached per (seed, overlay) paths."""
    key = (default_db_path(), overlay_db_path())
    if refresh or _db_cache.get("key") != key:
        base = CalibrationDB.load(key[0])
        overlay = CalibrationDB.load(key[1])
        _db_cache["key"] = key
        _db_cache["db"] = overlay.merged_over(base)
    return _db_cache["db"]


def clear_cache():
    """Drop the cached merged DB (tests / after a fit)."""
    _db_cache.clear()


def constants(device: Optional[str] = None) -> dict:
    """The calibration entry consumers price with: exact device kind
    first, then the :data:`GENERIC_DEVICE` entry, else empty (= shipped
    defaults everywhere)."""
    try:
        db = get_db()
        kinds = (device,) if device else (device_kind(), GENERIC_DEVICE)
        for dev in kinds:
            e = db.lookup(dev)
            if isinstance(e, dict):
                return e
    except Exception:  # pragma: no cover - pricing must never crash
        pass
    return {}


def _positive(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f > 0.0 and math.isfinite(f) else None


def link_bandwidth_override(link: str) -> Optional[float]:
    """Calibrated bytes/sec for one link class, or None to use the
    shipped ``mesh.LINK_BANDWIDTHS`` constant."""
    return _positive(constants().get("links", {})
                     .get(link, {}).get("bandwidth_bps"))


def link_latency_override(link: str) -> Optional[float]:
    """Calibrated fixed per-collective latency (seconds), or None."""
    try:
        v = float(constants().get("links", {})
                  .get(link, {}).get("latency_s"))
    except (TypeError, ValueError):
        return None
    return v if v >= 0.0 and math.isfinite(v) else None


def peak_flops_override() -> Optional[float]:
    """Calibrated effective peak FLOP/s, or None."""
    return _positive(constants().get("peak_flops_per_sec"))


def serving_rates() -> Optional[Tuple[float, float]]:
    """Calibrated (rows_per_s, batch_s) seeding the serving admission
    EWMA, or None when the DB has no serving entry."""
    e = constants().get("serving") or {}
    rate = _positive(e.get("rows_per_s"))
    if rate is None:
        return None
    try:
        batch_s = max(0.0, float(e.get("batch_s") or 0.0))
    except (TypeError, ValueError):
        batch_s = 0.0
    return rate, batch_s


# ---------------------------------------------------------------------------
# fitting
# ---------------------------------------------------------------------------

def fit_link(samples: List[Tuple[float, float]]
             ) -> Optional[Tuple[float, float, float]]:
    """Least-squares ``t = latency + bytes / bandwidth`` over
    ``(wire_bytes, seconds)`` samples -> (bandwidth_bps, latency_s,
    residual_rms_s), or None when the samples cannot pin a positive
    bandwidth. With one sample (or no byte spread) latency stays 0 and
    bandwidth is the aggregate bytes/second; a fit whose slope comes out
    non-positive (timing noise swamped the size sweep) falls back to the
    same through-origin estimate."""
    pts = [(float(b), float(t)) for b, t in samples
           if float(b) > 0.0 and float(t) > 0.0]
    if not pts:
        return None
    n = len(pts)
    sx = sum(b for b, _ in pts)
    sy = sum(t for _, t in pts)

    def _origin():
        bw = sx / sy
        resid = math.sqrt(sum((t - b / bw) ** 2 for b, t in pts) / n)
        return bw, 0.0, resid

    mx, my = sx / n, sy / n
    sxx = sum((b - mx) ** 2 for b, _ in pts)
    if n == 1 or sxx <= 0.0:
        return _origin()
    sxy = sum((b - mx) * (t - my) for b, t in pts)
    slope = sxy / sxx                 # seconds per byte = 1 / bandwidth
    intercept = my - slope * mx       # fixed latency
    if slope <= 0.0:
        return _origin()
    if intercept < 0.0:
        # negative latency is unphysical: refit the slope through origin
        slope = sum(b * t for b, t in pts) / sum(b * b for b, _ in pts)
        intercept = 0.0
        if slope <= 0.0:
            return _origin()
    bw = 1.0 / slope
    resid = math.sqrt(sum((t - (intercept + b / bw)) ** 2
                          for b, t in pts) / n)
    return bw, intercept, resid


def _median(xs: List[float]) -> Optional[float]:
    xs = sorted(x for x in xs if x > 0.0)
    if not xs:
        return None
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def fit(collective_samples: Optional[List[dict]] = None,
        compute_samples: Optional[List[dict]] = None,
        serving_samples: Optional[List[dict]] = None,
        save: bool = True, db_path: Optional[str] = None,
        device: Optional[str] = None) -> dict:
    """Regress measured runtimes into corrected model constants.

    - ``collective_samples``: ``{"link", "wire_bytes", "seconds"}`` per
      measured exchange -> per-link ``bandwidth_bps`` + ``latency_s``
      (:func:`fit_link`'s wire-model least squares).
    - ``compute_samples``: ``{"flops", "seconds"}`` per measured step ->
      ``peak_flops_per_sec`` = median(flops / seconds) — the effective
      rate the MFU denominator and the overlap model's compute stream
      should actually use on this backend.
    - ``serving_samples``: ``{"rows", "seconds"}`` per measured batch ->
      ``serving.rows_per_s`` / ``batch_s`` seeding the admission EWMA.

    Merges into the existing overlay entry for ``device`` (default: this
    process's device kind), saves atomically to ``db_path`` (default:
    the overlay path) when ``save``, and clears the DB cache so every
    consumer picks the constants up on its next pricing call. Returns
    ``{"device", "path", "entry"}``.
    """
    dev = device or device_kind()
    path = db_path or overlay_db_path()
    db = CalibrationDB.load(path) if save else get_db()
    entry = dict(db.lookup(dev) or {})

    fitted = dict(entry.get("fitted") or {})
    if collective_samples:
        by_link: Dict[str, List[Tuple[float, float]]] = {}
        for s in collective_samples:
            by_link.setdefault(str(s.get("link", "ici")), []).append(
                (float(s["wire_bytes"]), float(s["seconds"])))
        links = dict(entry.get("links") or {})
        for link, pts in sorted(by_link.items()):
            res = fit_link(pts)
            if res is None:
                continue
            bw, lat, resid = res
            links[link] = {"bandwidth_bps": bw, "latency_s": lat,
                           "residual_rms_s": resid, "n": len(pts)}
        entry["links"] = links
        fitted["n_collective"] = sum(len(v) for v in by_link.values())
    if compute_samples:
        peak = _median([float(s["flops"]) / float(s["seconds"])
                        for s in compute_samples
                        if float(s.get("seconds", 0.0)) > 0.0])
        if peak:
            entry["peak_flops_per_sec"] = peak
        fitted["n_compute"] = len(compute_samples)
    if serving_samples:
        rates = [float(s["rows"]) / float(s["seconds"])
                 for s in serving_samples
                 if float(s.get("seconds", 0.0)) > 0.0]
        rate = _median(rates)
        batch_s = _median([float(s["seconds"]) for s in serving_samples])
        if rate:
            entry["serving"] = {"rows_per_s": rate,
                                "batch_s": batch_s or 0.0}
        fitted["n_serving"] = len(serving_samples)
    entry["fitted"] = fitted

    if save:
        db.put(dev, entry)
        db.save(path)
        clear_cache()
    return {"device": dev, "path": path if save else None, "entry": entry}
