"""Chunked LM-head CE: forward and grads must match the dense
logits-materializing path exactly (fp32 accumulation both sides)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.chunked_ce import chunked_lm_ce

N, H, V = 24, 16, 1000   # V deliberately not a multiple of chunk


def _data(seed=0, ignore_frac=0.0):
    rs = np.random.RandomState(seed)
    hid = rs.randn(N, H).astype("f4")
    w = (rs.randn(H, V) * 0.05).astype("f4")
    y = rs.randint(0, V, N).astype("i4")
    if ignore_frac:
        y[rs.rand(N) < ignore_frac] = -100
    return jnp.asarray(hid), jnp.asarray(w), jnp.asarray(y)


def _dense_ce(hid, w, y, ignore_index=-100):
    logits = hid.astype(jnp.float32) @ w.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    valid = y != ignore_index
    safe = jnp.where(valid, y, 0)
    tgt = jnp.take_along_axis(logits, safe[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    per = jnp.where(valid, lse - tgt, 0.0)
    return per.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)


@pytest.mark.parametrize("chunk", [128, 256, 1000, 4096])
def test_forward_matches_dense(chunk):
    hid, w, y = _data()
    a = float(chunked_lm_ce(hid, w, y, chunk))
    b = float(_dense_ce(hid, w, y))
    assert a == pytest.approx(b, rel=1e-6)


def test_grads_match_dense():
    hid, w, y = _data(1)
    ga = jax.grad(lambda h, w: chunked_lm_ce(h, w, y, 256),
                  argnums=(0, 1))(hid, w)
    gb = jax.grad(lambda h, w: _dense_ce(h, w, y), argnums=(0, 1))(hid, w)
    np.testing.assert_allclose(np.asarray(ga[0]), np.asarray(gb[0]),
                               rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(ga[1]), np.asarray(gb[1]),
                               rtol=2e-5, atol=1e-7)


def test_ignore_index_and_bf16():
    hid, w, y = _data(2, ignore_frac=0.3)
    a = float(chunked_lm_ce(hid, w, y, 300))
    b = float(_dense_ce(hid, w, y))
    assert a == pytest.approx(b, rel=1e-6)
    # bf16 inputs: fp32 accumulation inside, grads in bf16
    hb, wb = hid.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    gh, gw = jax.grad(lambda h, w: chunked_lm_ce(h, w, y, 256),
                      argnums=(0, 1))(hb, wb)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    ref = float(_dense_ce(hb, wb, y))
    assert float(chunked_lm_ce(hb, wb, y, 256)) == \
        pytest.approx(ref, rel=1e-3)


def test_under_jit_and_all_ignored():
    hid, w, y = _data(3)
    f = jax.jit(lambda h, w, y: chunked_lm_ce(h, w, y, 256))
    assert np.isfinite(float(f(hid, w, y)))
    y_all = jnp.full_like(y, -100)
    assert float(f(hid, w, y_all)) == 0.0


def test_gpt_fused_head_loss_matches_logits_path():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.text.models import GPTForPretraining

    paddle.seed(0)
    m = GPTForPretraining(tensor_parallel=False, vocab_size=512,
                          hidden_size=64, num_layers=2, num_heads=4,
                          max_position_embeddings=64, attn_dropout=0.0,
                          hidden_dropout=0.0)
    m.eval()
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 512, (2, 32)), jnp.int32)
    y = jnp.asarray(rs.randint(0, 512, (2, 32)), jnp.int32)
    dense = float(nn.functional.cross_entropy(m(ids), y))
    fused = float(m.fused_head_loss(ids, y, chunk=128))
    assert fused == pytest.approx(dense, rel=1e-5)
