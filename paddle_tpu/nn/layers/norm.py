"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm running stats are Layer buffers: training forward reassigns them,
which the functionalization bridge captures as pure outputs under jit
(see paddle_tpu/jit/functionalization.py) — the TPU-native version of the
reference's in-place stat mutation in operators/batch_norm_op.cu.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from ..initializer import Constant, _to_initializer
from ..layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                initializer=_to_initializer(weight_attr, None) or Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), dtype=jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), dtype=jnp.float32))

    def forward(self, x):
        if self.training and not self.use_global_stats:
            out, new_rm, new_rv = F.batch_norm(
                x, self._mean, self._variance, self.weight, self.bias,
                training=True, momentum=self.momentum, epsilon=self.epsilon,
                data_format=self.data_format,
                use_global_stats=self.use_global_stats)
            self._mean = new_rm
            self._variance = new_rv
            return out
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=False, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm(num_channels) (reference: fluid/dygraph/nn.py)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, use_global_stats=False,
                 **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format in ("NCL", "NCW") else "NWC")


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BatchNorm (reference: nn/layer/norm.py SyncBatchNorm +
    operators/sync_batch_norm_op.cu).

    When running inside shard_map/pmap with a data-parallel axis named
    ``axis_name`` (default "data"), batch statistics are averaged over that
    axis with lax.pmean — the XLA collective replacing the reference's NCCL
    allreduce of partial sums.
    """

    axis_name = "data"

    def forward(self, x):
        import jax

        if not self.training or self.use_global_stats:
            return super().forward(x)
        try:
            jax.lax.axis_index(self.axis_name)  # raises if axis not bound
            in_spmd = True
        except Exception:
            in_spmd = False
        if not in_spmd:
            return super().forward(x)
        channel_axis = x.ndim - 1 if self.data_format[-1] == "C" else 1
        reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
        mean = jax.lax.pmean(jnp.mean(x, axis=reduce_axes), self.axis_name)
        mean_sq = jax.lax.pmean(jnp.mean(jnp.square(x), axis=reduce_axes),
                                self.axis_name)
        var = mean_sq - jnp.square(mean)
        self._mean = self.momentum * self._mean + (1 - self.momentum) * mean
        self._variance = self.momentum * self._variance + (1 - self.momentum) * var
        shape = [1] * x.ndim
        shape[channel_axis] = x.shape[channel_axis]
        import jax.lax as lax
        inv = lax.rsqrt(var + self.epsilon)
        out = (x - jnp.reshape(mean, shape)) * jnp.reshape(inv, shape)
        if self.weight is not None:
            out = out * jnp.reshape(self.weight.value, shape)
        if self.bias is not None:
            out = out + jnp.reshape(self.bias.value, shape)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm layers to SyncBatchNorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum, layer.epsilon,
                                data_format=layer.data_format)
            if layer.weight is not None:
                new.weight = layer.weight
            if layer.bias is not None:
                new.bias = layer.bias
            new._buffers["_mean"] = layer._mean
            new._buffers["_variance"] = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                initializer=_to_initializer(weight_attr, None) or Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            initializer=_to_initializer(weight_attr, None) or Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight, self.bias = None, None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                initializer=_to_initializer(weight_attr, None) or Constant(1.0))
            self.bias = None if bias_attr is False else self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral norm of a weight (reference: operators/spectral_norm_op.cc),
    power iteration on buffers u/v."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ...framework.random import get_rng_key
        import jax
        self.register_buffer("weight_u", jax.random.normal(get_rng_key(), (h,)))
        self.register_buffer("weight_v", jax.random.normal(get_rng_key(), (w,)))

    def forward(self, weight):
        import jax.numpy as jnp
        w = jnp.moveaxis(weight, self.dim, 0)
        h = w.shape[0]
        mat = jnp.reshape(w, (h, -1))
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        self.weight_u, self.weight_v = u, v
        sigma = u @ mat @ v
        return weight / sigma
