"""End-to-end Model.fit tests (reference: python/paddle/tests/test_model.py;
the MNIST-LeNet config is BASELINE.md config[0])."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import LeNet


def test_lenet_fit_learns():
    paddle.seed(123)

    class EasyData(FakeData):
        """Labels derivable from the image → learnable."""

        def __getitem__(self, idx):
            rng = np.random.RandomState(self.seed + idx)
            label = rng.randint(0, self.num_classes)
            img = np.zeros(self.image_shape, dtype=np.float32)
            img[0, label * 2:(label * 2 + 2), :] = 1.0
            img += rng.rand(*self.image_shape).astype(np.float32) * 0.1
            return img, np.asarray(label, dtype=np.int64)

    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(1e-3, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    train = EasyData(size=256)
    model.fit(train, epochs=3, batch_size=32, verbose=0)
    res = model.evaluate(EasyData(size=64, seed=999), batch_size=32, verbose=0)
    assert res["acc"] > 0.8, res


def test_fit_data_parallel_matches_single_device():
    """Model.fit under an active data>1 mesh shards batches over "data"
    (the hapi DataParallel analogue); trajectory must match single-device
    (same global batch, GSPMD averages the grads)."""
    from paddle_tpu.distributed.mesh import build_mesh

    def run(data_degree):
        build_mesh({"data": data_degree})
        paddle.seed(7)
        net = nn.Sequential(nn.Flatten(), nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.SGD(0.1, parameters=net.parameters()),
            nn.CrossEntropyLoss())
        rs = np.random.RandomState(0)
        x = rs.randn(64, 16).astype("float32")
        y = (x.sum(1) > 0).astype("int64") * 3
        losses = [model.train_batch([x], [y])[0] for _ in range(5)]
        return losses

    single = run(1)
    dp8 = run(8)
    np.testing.assert_allclose(single, dp8, rtol=2e-4)
    assert dp8[-1] < dp8[0]


def test_model_save_load(tmp_path):
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    ds = FakeData(size=32)
    model.fit(ds, epochs=1, batch_size=16, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    model.save(path)

    net2 = LeNet()
    model2 = paddle.Model(net2)
    model2.prepare(paddle.optimizer.SGD(0.1, parameters=net2.parameters()),
                   nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    model2.load(path)
    x = jnp.ones((2, 1, 28, 28))
    np.testing.assert_allclose(np.asarray(model.predict_batch(x)),
                               np.asarray(model2.predict_batch(x)),
                               rtol=1e-5, atol=1e-5)


def test_early_stopping_and_checkpoint(tmp_path):
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.0, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    ds = FakeData(size=32)
    cb = paddle.hapi.callbacks.EarlyStopping(monitor="loss", patience=0,
                                             save_best_model=False)
    model.fit(ds, eval_data=ds, epochs=4, batch_size=16, verbose=0,
              callbacks=[cb])
    assert model.stop_training


def test_dataloader_multiprocess():
    ds = FakeData(size=40)
    loader = paddle.io.DataLoader(ds, batch_size=8, num_workers=2)
    batches = list(loader)
    assert len(batches) == 5
    assert batches[0][0].shape == (8, 1, 28, 28)
    # determinism: same data as single-process
    loader1 = paddle.io.DataLoader(ds, batch_size=8, num_workers=0)
    b1 = list(loader1)
    np.testing.assert_allclose(b1[0][0], batches[0][0])


def test_jit_save_load(tmp_path):
    from paddle_tpu.jit import InputSpec
    net = LeNet()
    net.eval()
    path = str(tmp_path / "exported" / "lenet")
    paddle.jit.save(net, path, input_spec=[InputSpec([1, 1, 28, 28])])
    loaded = paddle.jit.load(path)
    x = jnp.ones((1, 1, 28, 28))
    np.testing.assert_allclose(np.asarray(net(x)), np.asarray(loaded(x)),
                               rtol=1e-5, atol=1e-5)


def test_to_static_traced_layer():
    net = LeNet()
    net.eval()
    traced = paddle.jit.to_static(net)
    x = jnp.ones((2, 1, 28, 28))
    np.testing.assert_allclose(np.asarray(traced(x)), np.asarray(net(x)),
                               rtol=1e-5, atol=1e-5)


def test_summary_and_flops():
    net = LeNet()
    info = paddle.summary(net, (1, 1, 28, 28))
    assert info["total_params"] > 0
    f = paddle.flops(net, (1, 1, 28, 28))
    assert f >= 0
