"""paddle.utils (reference: python/paddle/utils/ — download helpers,
deprecated decorator, unique_name, install_check run_check, cpp_extension).
"""
from __future__ import annotations

from ..framework.naming import unique_name  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .install_check import run_check  # noqa: F401

try:  # guard: needs a host toolchain
    from . import cpp_extension  # noqa: F401
except Exception:  # pragma: no cover
    cpp_extension = None
