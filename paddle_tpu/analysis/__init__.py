"""Jaxpr program analysis: rule-based linting + cost/memory estimation.

The reference platform runs IR passes over every Program before
execution; paddle_tpu's IR is the jaxpr and this package is that pass
layer. ``analyze()`` takes a function or an already-traced ClosedJaxpr,
runs every registered rule (see :mod:`.rules`) and the cost model
(:mod:`.cost`), and returns a :class:`~paddle_tpu.analysis.report.Report`
that renders as text or JSON.

Entry points around the repo:
- ``paddle_tpu.static.Program.analyze()`` — analyze a captured Program.
- ``ParallelTrainer.compile(..., analyze=True)`` — analyze the exact
  jitted train step (incl. comm_err / int8 grad-sync plumbing).
- ``tools/lint_program.py`` — CLI that stages the bench models and
  fails non-zero on error-severity findings.
"""
from __future__ import annotations

from typing import Iterable, Optional

from . import cost, report, rules, schedule, sharding, walker
from .report import CostRow, CostSummary, Finding, Report
from .rules import (RULES, AnalysisConfig, RuleContext, register_rule,
                    run_rules)
from .schedule import (FAMILIES, CollectiveSite, ProgramFamily,
                       ScheduleMismatch, crossrank_verify, extract_schedule,
                       program_fingerprint, register_family, verify_family)
from .sharding import ReshardSite, ShardingInfo, propagate, resharding_table
from .walker import count_eqns, walk

__all__ = [
    "analyze", "analyze_jaxpr", "AnalysisConfig", "Report", "Finding",
    "CostRow", "CostSummary", "RULES", "register_rule", "run_rules",
    "RuleContext", "walker", "rules", "cost", "report", "sharding",
    "schedule", "ReshardSite", "ShardingInfo", "propagate",
    "resharding_table", "CollectiveSite", "ProgramFamily", "FAMILIES",
    "ScheduleMismatch", "crossrank_verify", "extract_schedule",
    "program_fingerprint", "register_family", "verify_family",
]


def analyze_jaxpr(closed, mesh=None, donated=None,
                  config: Optional[AnalysisConfig] = None,
                  rule_ids: Optional[Iterable[str]] = None,
                  in_specs=None) -> Report:
    """Analyze an already-traced ClosedJaxpr. ``in_specs`` (one
    PartitionSpec/NamedSharding per flat invar) seeds the static
    sharding-propagation pass (:mod:`.sharding`); without it the
    sharding rules stay silent and the overlap model prices only
    explicit collectives."""
    cfg = config or AnalysisConfig()
    ctx = RuleContext(closed, mesh=mesh, donated=donated, config=cfg,
                      in_specs=in_specs)
    findings = run_rules(closed, config=cfg, rules=rule_ids, ctx=ctx)
    summary = cost.summarize(closed, k=cfg.top_k,
                             while_trips=cfg.while_trips)
    if mesh is not None:
        try:
            info = ctx.sharding()
            summary.overlap = cost.overlap_summary(
                closed, mesh, while_trips=cfg.while_trips,
                reshard_sites=info.sites if info is not None else None)
        except Exception:
            pass  # the overlap model must never sink an analysis run
    return Report(
        findings=findings,
        cost=summary,
        num_eqns=count_eqns(closed))


def analyze(target, *args, mesh=None, donated=None,
            config: Optional[AnalysisConfig] = None,
            rule_ids: Optional[Iterable[str]] = None,
            in_specs=None, **kwargs) -> Report:
    """Analyze a ClosedJaxpr, or trace ``target(*args, **kwargs)`` and
    analyze the result. Tracing uses abstract values only — pass
    ``jax.ShapeDtypeStruct`` args to analyze huge programs without
    materializing the data."""
    closed = target
    if not hasattr(target, "jaxpr") and callable(target):
        import jax
        closed = jax.make_jaxpr(target)(*args, **kwargs)
    return analyze_jaxpr(closed, mesh=mesh, donated=donated, config=config,
                         rule_ids=rule_ids, in_specs=in_specs)
