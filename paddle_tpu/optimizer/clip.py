"""Gradient clipping (reference: python/paddle/fluid/clip.py —
ClipGradByValue/Norm/GlobalNorm). Pure functions over grad pytrees, so they
compose into the jitted update; the distributed engine overrides the norm
reduction to span the whole mesh (HybridParallelClipGrad semantics,
reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:42).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads: dict) -> dict:
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, grads):
        return {k: jnp.clip(g, self.min, self.max) if g is not None else None
                for k, g in grads.items()}


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads):
        out = {}
        for k, g in grads.items():
            if g is None:
                out[k] = None
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out[k] = (g * scale).astype(g.dtype)
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        # Distributed hook: set by HybridParallelOptimizer to sum squared
        # norms across mesh axes (lax.psum) before scaling.
        self.norm_reduce_fn = None

    def global_norm_sq(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads.values() if g is not None]
        total = jnp.sum(jnp.stack(sq)) if sq else jnp.zeros(())
        if self.norm_reduce_fn is not None:
            total = self.norm_reduce_fn(total)
        return total

    def __call__(self, grads):
        total = self.global_norm_sq(grads)
        gnorm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return {k: (g * scale).astype(g.dtype) if g is not None else None
                for k, g in grads.items()}


def clip_grads(grads, clip):
    if clip is None:
        return grads
    return clip(grads)
