"""Decode-native serving e2e (DecodeServer + PagedKVCache + the toy
autoregressive model): every generation must reproduce the dense
no-cache oracle token-for-token — through prefix sharing, mixed
prefill/decode batches, LRU eviction, replica failover, and both
attention dispatch paths — while PR 10's zero-silent-loss
(``accounted``) and closed-recompile-set contracts keep holding.
"""
import time

import numpy as np
import pytest

from paddle_tpu.inference import serving
from paddle_tpu.inference.decode_model import (dense_generate,
                                               init_decode_model,
                                               make_step_fn)
from paddle_tpu.inference.kv_cache import PagedKVCache
from paddle_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()


PARAMS = init_decode_model(vocab=128, num_heads=2, head_dim=32, seed=7)
RS = np.random.RandomState(11)
SYSTEM = [int(t) for t in RS.randint(0, 128, 8)]    # 2 full pages @ ps=4


def prompt(i, extra=4):
    rs = np.random.RandomState(100 + i)
    return SYSTEM + [int(t) for t in rs.randint(0, 128, extra)]


def make_stack(num_pages=64, page_size=4, max_pages_per_seq=16,
               replicas=2, kernel="auto", interpret=False, **srv_kw):
    cache = PagedKVCache(num_pages, page_size, 2, 32)
    fn = make_step_fn(PARAMS, cache, kernel=kernel, interpret=interpret)
    cfg_kw = dict(max_batch=32, call_timeout_s=30.0, batch_wait_s=0.002)
    cfg_kw.update(srv_kw.pop("cfg_kw", {}))
    cfg = serving.ServingConfig(**cfg_kw)
    srv = serving.DecodeServer(fn, cache, replicas=replicas, config=cfg,
                               prefill_chunk=8,
                               max_pages_per_seq=max_pages_per_seq,
                               **srv_kw)
    return srv, cache


def oracle(p, n):
    return dense_generate(PARAMS, p, n)


def test_generations_match_dense_oracle_with_prefix_sharing():
    srv, cache = make_stack()
    with srv:
        # warm-up: registers the shared system-prompt pages
        warm = srv.submit_generate(prompt(0), 5)
        assert [int(t) for t in warm.result(timeout=30)[0]] \
            == oracle(prompt(0), 5)
        hits0 = cache.prefix_hit_tokens
        reqs = [srv.submit_generate(prompt(i), 5) for i in range(1, 6)]
        for i, r in zip(range(1, 6), reqs):
            assert [int(t) for t in r.result(timeout=30)[0]] \
                == oracle(prompt(i), 5), f"request {i} diverged"
        # every follower reused the 2 full system-prompt pages
        assert cache.prefix_hit_tokens - hits0 == 5 * 8
        assert srv.accounted()
        s = srv.stats()
        assert s["completed"] == 6 and s["decode_tokens"] == 30
        assert s["kv_cache"]["prefix_hit_tokens"] == cache.prefix_hit_tokens


def test_recompile_set_closes_after_warmup():
    srv, cache = make_stack()
    with srv:
        for i in range(4):
            r = srv.submit_generate(prompt(i), 4)
            r.result(timeout=30)
        warm = srv.stats()["recompiles"]
        assert warm > 0
        # identically-shaped second wave: ZERO new compiled shapes
        for i in range(4, 8):
            r = srv.submit_generate(prompt(i), 4)
            assert [int(t) for t in r.result(timeout=30)[0]] \
                == oracle(prompt(i), 4)
        assert srv.stats()["recompiles"] == warm
        assert srv.accounted()


def test_cache_pressure_sheds_as_deadline_infeasible_not_oom():
    # pool of 2 pages, but per-seq budget allows 8: a generation that
    # can NEVER fit is shed at admission with the standard cause
    srv, cache = make_stack(num_pages=2, page_size=4,
                            max_pages_per_seq=8)
    with srv:
        req = srv.submit_generate(list(np.arange(20) % 128), 4,
                                  deadline_s=5.0)
        assert req.state == "shed"
        assert req.cause == "deadline_infeasible"
        assert srv.stats()["shed_causes"]["deadline_infeasible"] == 1
        assert srv.accounted()
        assert cache.used_pages() == 0   # nothing leaked at admission


def test_over_budget_generation_rejected():
    srv, cache = make_stack(max_pages_per_seq=2, page_size=4)
    with srv:
        with pytest.raises(ValueError):
            srv.submit_generate(list(np.arange(12) % 128), 4)
        with pytest.raises(TypeError):
            srv.submit([np.zeros((1, 2), np.float32)])


def test_eviction_under_pressure_keeps_outputs_exact():
    # 6-page pool, up to 4 pages live per generation: completed
    # sequences leave registered pages behind, so later admissions must
    # evict — and the evictions may not corrupt any still-pinned page
    srv, cache = make_stack(num_pages=6, page_size=4,
                            max_pages_per_seq=4, replicas=1)
    with srv:
        for i in range(6):
            p = prompt(i * 17 + 1, extra=6)   # distinct 14-token prompts
            r = srv.submit_generate(p, 2)
            assert [int(t) for t in r.result(timeout=30)[0]] \
                == oracle(p, 2), f"generation {i} diverged"
        assert cache.evictions >= 1
        assert srv.accounted()
        s = srv.stats()["kv_cache"]
        assert s["evictions"] == cache.evictions


def test_terminal_paths_release_pages():
    srv, cache = make_stack()
    srv.start()
    r1 = srv.submit_generate(prompt(1), 4)
    r1.result(timeout=30)
    srv.shutdown(drain=True, timeout=30)
    late = srv.submit_generate(prompt(2), 4)
    assert late.state == "shed" and late.cause == "draining"
    assert srv.accounted()
    # every live reference is gone: remaining pages are exactly the
    # prefix table's (ref == 1 each), all evictable
    st = cache.stats()
    assert st["pages_used"] == st["registered"] == st["evictable"]
    assert cache.trim(cache.num_pages) == st["registered"]
    assert cache.used_pages() == 0


def test_failover_mid_decode_matches_oracle():
    srv, cache = make_stack(
        cfg_kw=dict(call_timeout_s=1.0, probation_base_s=0.02,
                    probation_max_s=0.2, seed=3))
    with srv:
        # warm both the jit caches and the EWMA so the stalled call's
        # timeout fires against a known-fast baseline; at_step=None
        # wedges the first batch dispatched inside the block (the global
        # batch counter has already moved past the warm-up)
        srv.submit_generate(prompt(0), 3).result(timeout=30)
        with faults.inject("replica_stall") as spec:
            reqs = [srv.submit_generate(prompt(i), 4) for i in (1, 2, 3)]
            for i, r in zip((1, 2, 3), reqs):
                assert [int(t) for t in r.result(timeout=60)[0]] \
                    == oracle(prompt(i), 4), f"request {i} diverged"
        assert spec.fired == 1
        s = srv.stats()
        assert s["failovers"] >= 1 and s["failed"] == 0
        assert srv.accounted()


def test_pallas_interpret_kernel_end_to_end():
    # the Pallas kernel needs sublane-aligned pages (ps % 8 == 0); the
    # 8-token system prompt is then exactly one shareable page
    srv, cache = make_stack(replicas=1, kernel="pallas", interpret=True,
                            page_size=8, max_pages_per_seq=8)
    with srv:
        srv.submit_generate(prompt(0), 3).result(timeout=60)  # warm-up
        r = srv.submit_generate(prompt(1), 3)
        assert [int(t) for t in r.result(timeout=60)[0]] \
            == oracle(prompt(1), 3)
        assert cache.prefix_hit_tokens >= 8
        assert srv.accounted()
