"""paddle_tpu.static — declarative (static-graph) facade.

Capability map (reference):
- ``Program`` / ProgramDesc            ← fluid/framework.py:4017 Program,
  framework/framework.proto:202 — here a Program IS a captured jaxpr
  (SURVEY.md §7: jaxprs + XLA replace ProgramDesc/Graph; no new IR).
- ``Executor.run(feed/fetch)``         ← fluid/executor.py:475,916 — here a
  cached jax.jit executable; the per-op interpreter loop
  (framework/executor.cc:166) dissolves into one XLA program.
- ``append_backward``                  ← fluid/backward.py:1377 — jax.grad.
- ``save/load_inference_model``        ← fluid/io.py:1246,1459 — StableHLO
  export via paddle_tpu.jit.
- ``CompiledProgram``                  ← fluid/compiler.py — pjit over a mesh
  replaces the multi-device ParallelExecutor build.

Design note: the reference builds programs *imperatively* — layer calls
append OpDescs to a global block. On TPU the same declarative capability is
reached by TRACING: the network is an ordinary Python function (eager
semantics, same code as dygraph — the dual-mode split collapses), and
``Program.trace(fn, specs)`` stages it once into a jaxpr. ``static.data``
declares the feed placeholders; names bind feeds at run time.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..jit import InputSpec

__all__ = [
    "InputSpec", "data", "Program", "Executor", "CompiledProgram",
    "default_main_program", "program_guard", "append_backward", "gradients",
    "save_inference_model", "load_inference_model", "name_scope", "cpu_places",
    "device_count",
]


def data(name: str, shape, dtype="float32") -> InputSpec:
    """Declare a named feed placeholder (reference: paddle.static.data,
    fluid/layers/io.py data). Returns an InputSpec consumed by
    ``Program.trace``; the name binds ``feed={name: value}`` at run time."""
    return InputSpec(shape, dtype=dtype, name=name)


class Program:
    """A staged computation: ordered feed specs + traced pure function.

    reference: fluid/framework.py:4017. ``trace`` is the only constructor
    that populates it; an empty Program exists for program_guard parity.
    """

    def __init__(self):
        self._fn: Optional[Callable] = None
        self._specs: "OrderedDict[str, InputSpec]" = OrderedDict()
        self._jaxpr = None
        self._fetch_names: List[str] = []
        self._compiled: Optional[Callable] = None  # set by Executor

    @classmethod
    def trace(cls, fn: Callable, *specs: InputSpec, fetch_names=None,
              static_batch: Optional[int] = None) -> "Program":
        """Capture ``fn(*arrays) -> output(s)`` as a Program. ``specs`` come
        from ``static.data`` (order = positional argument order)."""
        from ..framework import naming
        prog = cls()
        prog._fn = fn
        # auto-generated layer names must be IDENTICAL on every (re)trace of
        # this program, or each trace would mint a fresh parameter set
        prog._name_state = dict(naming._namer.counters)
        for i, s in enumerate(specs):
            name = s.name or f"x{i}"
            prog._specs[name] = s
        shapes = [s.to_shape_dtype(static_batch or 1) for s in specs]
        # first trace COMMITS its counter advance (the next program traced
        # must not collide on fc_0); replays below restore
        with naming.guard(initial=prog._name_state, commit=True):
            prog._jaxpr = jax.make_jaxpr(fn)(*shapes)
        with prog._naming():
            outs = jax.eval_shape(fn, *shapes)
        n_out = len(outs) if isinstance(outs, (tuple, list)) else 1
        prog._fetch_names = list(fetch_names or
                                 [f"fetch_{i}" for i in range(n_out)])
        return prog

    def _naming(self):
        """Replay the trace-time name counters (restoring after), so
        retraces bind fc_0 to the same parameters instead of minting fc_1."""
        from ..framework import naming
        return naming.guard(
            initial=getattr(self, "_name_state", None), commit=False)

    # -- introspection (ProgramDesc analogues) ----------------------------
    @property
    def feed_names(self) -> List[str]:
        return list(self._specs)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def num_ops(self) -> int:
        """Equation count, recursive through inner jaxprs (pjit/scan/
        cond/... bodies) — the ProgramDesc op count, not just block 0."""
        if self._jaxpr is None:
            return 0
        from ..analysis.walker import count_eqns
        return count_eqns(self._jaxpr)

    def analyze(self, mesh=None, config=None):
        """Run the jaxpr analyzer (paddle_tpu.analysis) over this
        Program: rule findings + cost/memory estimate as a Report."""
        if self._jaxpr is None:
            from ..analysis import Report
            return Report()
        from ..analysis import analyze_jaxpr
        return analyze_jaxpr(self._jaxpr, mesh=mesh, config=config)

    def to_string(self, throw_on_error=True, with_details=False) -> str:
        return "<empty Program>" if self._jaxpr is None else str(self._jaxpr)

    __str__ = to_string

    def __repr__(self) -> str:
        if self._jaxpr is None:
            return "<Program: empty>"
        try:
            summary = self.analyze().summary()
        except Exception:
            summary = "analysis unavailable"
        return (f"<Program: {len(self._specs)} feeds, "
                f"{len(self._fetch_names)} fetches, {self.num_ops()} ops; "
                f"{summary}>")

    def clone(self, for_test: bool = False) -> "Program":
        import copy
        return copy.copy(self)


_default_main = Program()


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    """Parameter init is eager here (initializers run at Layer construction);
    the startup program (fluid/framework.py default_startup_program) has no
    work left to do — returned for API parity."""
    return Program()


class program_guard:
    """reference: fluid/framework.py program_guard. Swaps the default main
    program; network code inside the guard should be wrapped into a function
    and staged with ``Program.trace`` (see module docstring)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self._prog = main_program

    def __enter__(self):
        global _default_main
        self._saved = _default_main
        _default_main = self._prog
        return self._prog

    def __exit__(self, *exc):
        global _default_main
        _default_main = self._saved
        return False


def append_backward(loss_fn: Callable, wrt=0) -> Callable:
    """Given a scalar-valued ``loss_fn``, return ``grad_fn`` computing
    d loss / d args[wrt] (reference: fluid/backward.py:1377 — which walks the
    ProgramDesc emitting grad ops; jax.grad derives the same from the jaxpr)."""
    return jax.grad(loss_fn, argnums=wrt)


def gradients(loss_fn: Callable, wrt=0) -> Callable:
    return append_backward(loss_fn, wrt)


class Executor:
    """Session-style runner (reference: fluid/executor.py:475 Executor,
    :916 run). Compiles (once, cached per Program + shapes) and executes."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True):
        program = program or default_main_program()
        if program._fn is None:
            raise ValueError("Program is empty — build it with Program.trace "
                             "(see paddle_tpu.static docstring)")
        feed = feed or {}
        try:
            args = [jnp.asarray(feed[name]) for name in program.feed_names]
        except KeyError as e:
            raise KeyError(f"missing feed {e} (program feeds: "
                           f"{program.feed_names})") from None
        # compiled executable lives on the Program (an id()-keyed cache here
        # could alias a new Program at a recycled address). Scope parameters
        # enter as jit ARGUMENTS (not closure constants) so static.load /
        # set_program_state take effect without retracing.
        scope = global_scope()
        # compiled cache is keyed by the scope OBJECT: the jitted closure
        # binds one base scope (for new-parameter writes at trace time), so
        # running under a different scope_guard must compile a fresh entry
        if not isinstance(program._compiled, dict):
            program._compiled = {}
        entry = program._compiled.get(id(scope))
        if entry is None or entry[0] is not scope:
            def pure(state, *feed_args):
                overlay = _OverlayScope(scope, state)
                _scope_stack.append(overlay)
                try:
                    with program._naming():
                        return program._fn(*feed_args)
                finally:
                    _scope_stack.pop()
            entry = (scope, jax.jit(pure))
            program._compiled[id(scope)] = entry
        state = _scope_state(scope)
        outs = entry[1](state, *args)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        if fetch_list:
            name_to_i = {n: i for i, n in enumerate(program.fetch_names)}
            sel = []
            for f in fetch_list:
                if isinstance(f, str) and f in name_to_i:
                    sel.append(outs[name_to_i[f]])
                elif isinstance(f, int):
                    sel.append(outs[f])
                else:
                    raise KeyError(f"unknown fetch {f!r} (have "
                                   f"{program.fetch_names})")
            outs = sel
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return list(outs)

    def train_from_dataset(self, program, dataset, sparse_table,
                           dense_table=None, thread: int = 2,
                           batch_size: int = 128, lr: float = 0.05,
                           worker: str = "hogwild", key_slot: str = "ids",
                           extract=None, _eval_only: bool = False,
                           **desc_kwargs):
        """reference: fluid/executor.py train_from_dataset — dispatch the
        Trainer/DeviceWorker runtime (trainer.h:57) over a Dataset. Here
        ``program`` is the jitted step callable
        ``(emb, dense, batch) -> (loss, emb_grad, dense_grad)`` — the dense
        compute the reference expressed as a ProgramDesc — and the sparse
        side is a native/RPC table (distributed/ps). ``key_slot``/``extract``
        select which slot feeds the embedding pull. Returns the trainer's
        stats dict (loss_mean/losses/batches/threads)."""
        from ..distributed.ps.trainer import TrainerDesc, TrainerFactory
        desc = TrainerDesc(worker=worker, thread_num=thread,
                           batch_size=batch_size, lr=lr, **desc_kwargs)
        return TrainerFactory().create(desc).train(
            dataset, program, sparse_table, dense_table=dense_table,
            key_slot=key_slot, extract=extract, eval_only=_eval_only)

    def infer_from_dataset(self, program, dataset, sparse_table,
                           dense_table=None, thread: int = 2,
                           batch_size: int = 128, key_slot: str = "ids",
                           extract=None):
        """reference: executor.py infer_from_dataset — same worker fan-out,
        read-only: no pushes reach the tables (even zero grads would advance
        Adam step/moment decay) and unseen ids are not materialized."""
        return self.train_from_dataset(program, dataset, sparse_table,
                                       dense_table=dense_table,
                                       thread=thread, batch_size=batch_size,
                                       lr=0.0, key_slot=key_slot,
                                       extract=extract, _eval_only=True)

    def close(self):
        pass


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram → ParallelExecutor.
    On TPU multi-device execution is pjit/GSPMD: wrap a Program and it runs
    jitted over the active mesh with sharded feeds handled by XLA."""

    def __init__(self, program: Program, build_strategy=None):
        self._program = program
        self.build_strategy = build_strategy

    def __getattr__(self, item):
        return getattr(self._program, item)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program: Optional[Program] = None, **kwargs):
    """Export a traced Program (StableHLO + empty params blob) —
    reference: fluid/io.py:1246 save_inference_model."""
    from jax import export as jax_export
    import os
    import pickle

    program = program or default_main_program()
    if program._fn is None:
        raise ValueError("Program is empty")
    from ..jit import poly_arg_specs
    specs = list(program._specs.values())
    args = [s.to_shape_dtype(1) for s in specs]
    exported = jax_export.export(jax.jit(program._fn))(
        *poly_arg_specs(specs, args))
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".stablehlo", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"feed_names": program.feed_names,
                     "fetch_names": program.fetch_names}, f)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (program_like_callable, feed_names, fetch_names)
    (reference: fluid/io.py:1459)."""
    from jax import export as jax_export
    import pickle

    with open(path_prefix + ".stablehlo", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)

    def run(*args):
        return exported.call(*args)

    return run, meta["feed_names"], meta["fetch_names"]


def name_scope(prefix: str):
    return jax.named_scope(prefix)


def cpu_places(device_count: Optional[int] = None):
    devs = jax.devices("cpu") if any(
        d.platform == "cpu" for d in jax.devices()) else []
    return devs[:device_count] if device_count else devs


def device_count() -> int:
    return jax.device_count()


# -- Scope / variable store ---------------------------------------------------
class Variable:
    """Static-graph variable handle (reference fluid/framework.py:805). Here
    it names an entry in a Scope; values are jax.Arrays."""

    def __init__(self, name, shape=None, dtype="float32", persistable=False):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.persistable = persistable

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.shape})"


class Scope:
    """Name → value store (reference framework/scope.h:173: name→Variable
    map with parent chain)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._parent = parent

    def var(self, name: str, value=None):
        if value is not None:
            self._vars[name] = value
        else:
            self._vars.setdefault(name, None)
        return self._vars.get(name)

    def find_var(self, name: str):
        if name in self._vars:
            return self._vars[name]
        return self._parent.find_var(name) if self._parent else None

    def local_var_names(self):
        return list(self._vars)

    def new_scope(self) -> "Scope":
        return Scope(parent=self)

    def drop_kids(self):
        pass

    # dict-ish
    def __contains__(self, name):
        return self.find_var(name) is not None


_global_scope = Scope()
_scope_stack: List[Scope] = [_global_scope]


class _OverlayScope(Scope):
    """Trace-time view of a Scope: reads come from a (possibly traced) state
    dict so parameters are jit inputs; writes (new-parameter creation during
    trace, which _param keeps concrete) land in the base scope."""

    def __init__(self, base: Scope, state: Dict[str, object]):
        super().__init__(parent=base)
        self._base = base
        self._state = state

    def find_var(self, name: str):
        if name in self._state:
            return self._state[name]
        return self._base.find_var(name)

    def var(self, name: str, value=None):
        return self._base.var(name, value)

    def local_var_names(self):
        return list(self._state) + self._base.local_var_names()


def _scope_state(scope: Scope) -> Dict[str, object]:
    """Array-valued vars visible from ``scope`` (walking the parent chain)."""
    state = {}
    cur = scope
    while cur is not None:
        for k in cur.local_var_names():
            if k not in state:
                v = cur.find_var(k)
                if v is not None and hasattr(v, "shape") and \
                        hasattr(v, "dtype"):
                    state[k] = v
        cur = cur._parent
    return state


def global_scope() -> Scope:
    """reference fluid/executor.py global_scope()."""
    return _scope_stack[-1]


class scope_guard:
    """reference fluid/executor.py scope_guard."""

    def __init__(self, scope: Scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()
        return False


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference fluid/layers/tensor.py create_global_var."""
    from ..framework.naming import unique_name
    name = name or unique_name("global_var")
    arr = jnp.full(tuple(shape), value, dtype=jnp.dtype(dtype))
    global_scope().var(name, arr)
    return Variable(name, shape, dtype, persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference static create_parameter — registers in the global scope."""
    from .nn import _param
    from ..framework.naming import unique_name
    name = name or unique_name("parameter")
    return _param(name, tuple(shape), dtype,
                  initializer=default_initializer, is_bias=is_bias)


# -- program/state serialization ---------------------------------------------
def load_program_state(model_path: str, var_list=None):
    """reference fluid/io.py load_program_state — returns name→ndarray."""
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    if var_list is not None:
        names = {v.name if hasattr(v, "name") else v for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def set_program_state(program, state_dict):
    """reference fluid/io.py set_program_state — write into global scope."""
    scope = global_scope()
    for k, v in state_dict.items():
        scope.var(k, jnp.asarray(v))


def save(program, model_path: str, protocol=4, **configs):
    """reference static save (fluid/io.py save): persist every scope value
    + the program meta."""
    import os
    import pickle
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    scope = global_scope()
    state = {k: np.asarray(scope.find_var(k))
             for k in scope.local_var_names()
             if scope.find_var(k) is not None}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump({"feed_names": getattr(program, "feed_names", []),
                     "fetch_names": getattr(program, "fetch_names", [])}, f,
                    protocol=protocol)


def load(program, model_path: str, executor=None, var_list=None):
    """reference static load (fluid/io.py load)."""
    set_program_state(program, load_program_state(model_path,
                                                  var_list=var_list))


def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      **kwargs) -> bytes:
    """reference static/io.py serialize_program."""
    import pickle
    program = program or default_main_program()
    return pickle.dumps({"feed_names": program.feed_names,
                         "fetch_names": program.fetch_names,
                         "text": program.to_string(False)})


def deserialize_program(data: bytes):
    import pickle
    meta = pickle.loads(data)
    prog = Program()
    prog._fetch_names = meta.get("fetch_names", [])
    return prog


def serialize_persistables(feed_vars=None, fetch_vars=None, executor=None,
                           program=None, **kwargs) -> bytes:
    import pickle
    scope = global_scope()
    state = {k: np.asarray(scope.find_var(k))
             for k in scope.local_var_names()
             if scope.find_var(k) is not None}
    return pickle.dumps(state)


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle
    set_program_state(program, pickle.loads(data))


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars=None, fetch_vars=None, **kwargs):
    """reference static/io.py normalize_program — prune/dedup for export.
    jaxpr programs are already pruned by tracing; identity."""
    return program


# -- strategies / multi-device shims -----------------------------------------
class BuildStrategy:
    """reference details/build_strategy.h — pass-pipeline knobs. XLA owns
    fusion/memory passes, so these are accepted-and-recorded only."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = None
        self.gradient_scale_strategy = None


class ExecutionStrategy:
    """reference details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.use_thread_barrier = False


class ParallelExecutor:
    """reference framework/parallel_executor.cc — multi-device SSA executor.
    On TPU this is pjit/GSPMD: wraps a Program; run() jits over the active
    mesh (SURVEY.md §7: ParallelExecutor → pjit)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, **kwargs):
        self._program = main_program or default_main_program()
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return Executor().run(self._program, feed=feed,
                              fetch_list=fetch_list,
                              return_numpy=return_numpy)


class device_guard:
    """reference framework.py device_guard — pin ops to a device. Under XLA,
    placement is whole-computation (jax.default_device)."""

    def __init__(self, device=None):
        self._device = device
        self._cm = None

    def __enter__(self):
        if self._device and self._device.startswith("cpu"):
            self._cm = jax.default_device(jax.devices("cpu")[0])
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm:
            self._cm.__exit__(*exc)
        return False


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """reference fluid/layers/control_flow.py Print →
    jax.debug.print (works under jit)."""
    jax.debug.print((message or "") + " {x}", x=input)
    return input


def py_func(func, x, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    from .nn import py_func as _py_func
    return _py_func(func, x, out=out, backward_func=backward_func,
                    skip_vars_in_backward_input=skip_vars_in_backward_input)


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=200, topk=1,
        slide_steps=1):
    """Batch AUC (reference fluid/layers/metric_op.py auc) — returns
    (auc_value, batch_auc_value, [state]) simplified to the value."""
    from ..metric import Auc as _Auc
    m = _Auc(num_thresholds=num_thresholds)
    m.update(np.asarray(input), np.asarray(label))
    return jnp.asarray(m.accumulate(), jnp.float32)


def cuda_places(device_ids=None):
    """Accelerator devices (reference fluid/framework.py cuda_places —
    maps to the TPU/accelerator devices here)."""
    devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    if device_ids is None:
        return devs
    return [devs[i] for i in device_ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


class WeightNormParamAttr:
    """reference fluid/param_attr.py WeightNormParamAttr — weight-norm
    reparameterization config (consumed by nn initializer machinery)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable


from . import nn  # noqa: F401,E402
from . import amp  # noqa: F401,E402

__all__ += [
    "Variable", "Scope", "global_scope", "scope_guard", "create_global_var",
    "create_parameter", "load_program_state", "set_program_state", "save",
    "load", "serialize_program", "deserialize_program",
    "serialize_persistables", "deserialize_persistables", "save_to_file",
    "load_from_file", "normalize_program", "BuildStrategy",
    "ExecutionStrategy", "ParallelExecutor", "device_guard", "Print",
    "py_func", "accuracy", "auc", "cuda_places", "xpu_places",
    "WeightNormParamAttr", "nn", "default_startup_program",
]
