"""Multi-device collective round-trips + the compressed gradient exchange.

Runs on the 8-device virtual CPU mesh (conftest.py forces
--xla_force_host_platform_device_count=8): every collective is exercised
inside a real shard_map trace so the test covers the exact lowering the
training engine uses, not an eager approximation.

Covers the paired send/recv ring fix (a send/recv pair must compose to
identity), the gather-free broadcast/PROD rewrites, the int8
quantize->dequantize error bound, error-feedback accumulation, the
bucketed compressed_tree_mean (exactness, dtype grouping, bucket-split
invariance), and the end-to-end engine/DataParallel/LocalSGD plumbing —
including the acceptance bar: int8+EF training loss within 2% of fp32
after a fixed number of steps.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import collective
from paddle_tpu.distributed.collective import ReduceOp
from paddle_tpu.distributed.compressed import (
    INT16_SAFE_RANKS, bucket_sizes, compressed_psum_scatter,
    compressed_tree_mean, dequantize_int4_blocks, dequantize_int8_blocks,
    init_residuals, int4_accum_dtype, normalize_axis_policies, pack_int4,
    quantize_int4_blocks, quantize_int8_blocks, unpack_int4,
    wire_bytes_per_rank)
from paddle_tpu.distributed.engine import ParallelTrainer
from paddle_tpu.distributed.fleet.utils import fused_allreduce_gradients
from paddle_tpu.distributed.mesh import build_mesh, set_axis_links
from paddle_tpu.distributed.meta_parallel.localsgd import LocalSGDTrainer
from paddle_tpu.distributed.parallel import DataParallel

N = 4  # subgroup size used by most tests (8 virtual devices available)


def spmd(fn, *arrays, n=N, out_specs=None):
    """Run fn per-rank: each array's leading dim splits over 'data'."""
    mesh = build_mesh({"data": n})
    in_specs = tuple(P("data", *([None] * (np.ndim(a) - 1)))
                     for a in arrays)
    if out_specs is None:
        out_specs = in_specs[0]
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*arrays)


# ---------------------------------------------------------------------------
# collective round-trips
# ---------------------------------------------------------------------------

class TestCollectiveRoundTrips:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.x = rng.randn(N, 6).astype(np.float32)

    def test_send_recv_pair_is_identity(self):
        """recv must invert send: previously both shifted +1, so the pair
        moved data TWO ranks around the ring."""
        out = spmd(lambda x: collective.recv(collective.send(x)),
                   jnp.asarray(self.x))
        np.testing.assert_array_equal(np.asarray(out), self.x)

    def test_send_shifts_plus_one(self):
        out = np.asarray(spmd(collective.send, jnp.asarray(self.x)))
        for i in range(N):
            np.testing.assert_array_equal(out[(i + 1) % N], self.x[i])

    def test_recv_shifts_minus_one(self):
        out = np.asarray(spmd(collective.recv, jnp.asarray(self.x)))
        for i in range(N):
            np.testing.assert_array_equal(out[(i - 1) % N], self.x[i])

    @pytest.mark.parametrize("src", range(N))
    def test_broadcast_from_each_src(self, src):
        out = np.asarray(spmd(
            lambda x: collective.broadcast(x, src=src), jnp.asarray(self.x)))
        for i in range(N):
            np.testing.assert_allclose(out[i], self.x[src], rtol=1e-6)

    def test_allreduce_avg(self):
        out = np.asarray(spmd(
            lambda x: collective.all_reduce(x, op=ReduceOp.AVG),
            jnp.asarray(self.x)))
        want = self.x.mean(axis=0, keepdims=True)
        for i in range(N):
            np.testing.assert_allclose(out[i:i + 1], want, rtol=1e-5)

    def test_allreduce_prod_with_negatives_and_zeros(self):
        x = self.x.copy()
        x[1] *= -1.0
        x[2, 3] = 0.0
        out = np.asarray(spmd(
            lambda v: collective.all_reduce(v, op=ReduceOp.PROD),
            jnp.asarray(x)))
        want = np.prod(x, axis=0)
        for i in range(N):
            np.testing.assert_allclose(out[i], want, rtol=1e-4, atol=1e-6)

    def test_allreduce_prod_int_exact(self):
        """The ring-multiply rewrite must stay exact for integer dtypes
        (the gathered-stack version was, the rewrite must not regress)."""
        rng = np.random.RandomState(1)
        x = rng.randint(-3, 4, (N, 5)).astype(np.int32)
        out = np.asarray(spmd(
            lambda v: collective.all_reduce(v, op=ReduceOp.PROD),
            jnp.asarray(x)))
        want = np.prod(x, axis=0)
        for i in range(N):
            np.testing.assert_array_equal(out[i], want)

    def test_reduce_scatter(self):
        # local (N, k) per rank; tiled psum_scatter: rank i keeps block i
        # of the rank-sum -> global out (N, k)
        rng = np.random.RandomState(2)
        x = rng.randn(N * N, 3).astype(np.float32)
        mesh = build_mesh({"data": N})
        out = jax.shard_map(
            lambda v: collective.reduce_scatter(v),
            mesh=mesh, in_specs=P("data", None), out_specs=P("data", None),
            check_vma=False)(jnp.asarray(x))
        xr = x.reshape(N, N, 3)           # [rank, block, k]
        want = xr.sum(axis=0)             # [block, k]
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    def test_alltoall_is_involution(self):
        rng = np.random.RandomState(3)
        x = rng.randn(N * N, 4).astype(np.float32)
        mesh = build_mesh({"data": N})
        f = jax.shard_map(
            lambda v: collective.alltoall(collective.alltoall(v)),
            mesh=mesh, in_specs=P("data", None), out_specs=P("data", None),
            check_vma=False)
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))), x,
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# quantization + error feedback
# ---------------------------------------------------------------------------

class TestQuantization:
    def test_roundtrip_error_bounded_by_half_scale(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1024).astype(np.float32) * 7.0)
        q, s = quantize_int8_blocks(x, block=64)
        deq = dequantize_int8_blocks(q, s, block=64)
        err = np.abs(np.asarray(x - deq)).reshape(-1, 64)
        bound = np.asarray(s)[:, None] / 2 + 1e-7
        assert (err <= bound).all(), (err.max(), bound.min())

    def test_shared_scale_path(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(256).astype(np.float32))
        _, s = quantize_int8_blocks(x, block=64)
        q2, s2 = quantize_int8_blocks(x * 0.5, block=64, scale=s)
        assert np.asarray(s2) is not None
        np.testing.assert_array_equal(np.asarray(s2), np.asarray(s))
        assert np.abs(np.asarray(q2)).max() <= 127

    def test_zero_block_quantizes_to_zero(self):
        x = jnp.zeros(128, jnp.float32)
        q, s = quantize_int8_blocks(x, block=64)
        assert np.asarray(q).max() == 0
        deq = dequantize_int8_blocks(q, s, block=64)
        np.testing.assert_array_equal(np.asarray(deq), np.zeros(128))

    def test_error_feedback_reduces_cumulative_error(self):
        """With EF the quantization error is carried into the next step, so
        the SUM of T exchanged means tracks the true sum much more tightly
        than T independent (no-EF) exchanges — the DGC property."""
        rng = np.random.RandomState(4)
        g = rng.randn(N, 512).astype(np.float32)
        true_mean = g.mean(axis=0)
        T = 16
        mesh = build_mesh({"data": N})

        def step(x, res):
            tree, new_res = compressed_tree_mean(
                {"g": x[0]}, "data", policy="int8", block=16,
                residuals={"g": res[0]} if res is not None else None)
            out = tree["g"][None]
            return (out, new_res["g"][None]) if res is not None \
                else (out, jnp.zeros_like(x))

        f_ef = jax.jit(jax.shard_map(
            lambda x, r: step(x, r), mesh=mesh,
            in_specs=(P("data", None), P("data", None)),
            out_specs=(P("data", None), P("data", None)),
            check_vma=False))
        f_no = jax.jit(jax.shard_map(
            lambda x: step(x, None)[0], mesh=mesh,
            in_specs=P("data", None), out_specs=P("data", None),
            check_vma=False))

        res = jnp.zeros_like(jnp.asarray(g))
        acc_ef = np.zeros_like(true_mean)
        for _ in range(T):
            out, res = f_ef(jnp.asarray(g), res)
            acc_ef += np.asarray(out)[0]
        out_no = np.asarray(f_no(jnp.asarray(g)))[0]
        err_ef = np.abs(acc_ef / T - true_mean).max()
        err_no = np.abs(out_no - true_mean).max()
        assert err_ef < err_no / 3, (err_ef, err_no)

    def test_init_residuals_shapes(self):
        tree = {"a": jnp.ones((3, 4), jnp.bfloat16), "b": jnp.ones((5,))}
        res = init_residuals(tree)
        assert res["a"].shape == (3, 4) and res["a"].dtype == jnp.float32
        assert res["b"].shape == (5,)


# ---------------------------------------------------------------------------
# compressed_tree_mean
# ---------------------------------------------------------------------------

def _tree_mean_spmd(tree_stacked, policy, block=32, bucket_bytes=4 << 20,
                    n=N):
    """Run compressed_tree_mean over 'data' on a replica-major tree."""
    mesh = build_mesh({"data": n})
    specs = jax.tree_util.tree_map(
        lambda v: P("data", *([None] * (np.ndim(v) - 1))), tree_stacked)

    def f(t):
        local = jax.tree_util.tree_map(lambda v: v[0], t)
        mean, _ = compressed_tree_mean(local, "data", policy=policy,
                                       block=block,
                                       bucket_bytes=bucket_bytes)
        return jax.tree_util.tree_map(lambda v: v[None], mean)

    return jax.shard_map(f, mesh=mesh, in_specs=(specs,),
                         out_specs=specs, check_vma=False)(tree_stacked)


class TestCompressedTreeMean:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.tree = {
            "w": jnp.asarray(rng.randn(N, 8, 16).astype(np.float32)),
            "b": jnp.asarray(rng.randn(N, 5).astype(np.float32)),
            "h": jnp.asarray(rng.randn(N, 33).astype(np.float32))
            .astype(jnp.bfloat16),
        }

    def _exact(self):
        return {k: np.asarray(v.astype(jnp.float32)).mean(axis=0)
                for k, v in self.tree.items()}

    def test_fp32_policy_matches_pmean_exactly(self):
        out = _tree_mean_spmd(self.tree, "fp32")
        want = self._exact()
        for k in ("w", "b"):
            got = np.asarray(out[k])
            for i in range(N):
                np.testing.assert_allclose(got[i], want[k], rtol=1e-6)

    def test_bf16_policy_close(self):
        out = _tree_mean_spmd(self.tree, "bf16")
        want = self._exact()
        got = np.asarray(out["w"])
        np.testing.assert_allclose(got[0], want["w"], rtol=2e-2, atol=2e-2)

    def test_int8_policy_close(self):
        out = _tree_mean_spmd(self.tree, "int8")
        want = self._exact()
        got = np.asarray(out["w"])
        scale = np.abs(want["w"]).max()
        assert np.abs(got[0] - want["w"]).max() < 0.05 * scale

    def test_int8_rank_consistent(self):
        """Every rank must reconstruct the SAME mean (all_gathered)."""
        out = np.asarray(_tree_mean_spmd(self.tree, "int8")["w"])
        for i in range(1, N):
            np.testing.assert_array_equal(out[0], out[i])

    @pytest.mark.slow
    def test_bucket_split_invariance(self):
        """Bucket boundaries are block-aligned, so splitting into many
        small buckets must be bit-identical to one big bucket."""
        big = _tree_mean_spmd(self.tree, "int8", bucket_bytes=64 << 20)
        small = _tree_mean_spmd(self.tree, "int8", bucket_bytes=512)
        for k in self.tree:
            np.testing.assert_array_equal(
                np.asarray(big[k].astype(jnp.float32)),
                np.asarray(small[k].astype(jnp.float32)))

    def test_non_float_leaves_pass_through_pmean(self):
        tree = {"c": jnp.tile(jnp.arange(4, dtype=jnp.int32)[None],
                              (N, 1))}
        out = _tree_mean_spmd(tree, "int8")
        np.testing.assert_array_equal(np.asarray(out["c"][0]),
                                      np.arange(4, dtype=np.int32))

    def test_unbound_axis_is_identity(self):
        tree = {"w": jnp.ones((4,))}
        out, res = compressed_tree_mean(tree, "data", policy="int8")
        np.testing.assert_array_equal(np.asarray(out["w"]), np.ones(4))
        assert res is None

    def test_bad_policy_raises(self):
        with pytest.raises(ValueError):
            compressed_tree_mean({"w": jnp.ones(4)}, "data", policy="fp8")

    def test_bucket_sizes_alignment(self):
        sizes = bucket_sizes(10 * 128, 3 * 128, 128)
        assert sum(sizes) == 10 * 128
        assert all(s % 128 == 0 for s in sizes)

    def test_wire_bytes_ratio_exceeds_3p5(self):
        fp32 = wire_bytes_per_rank(1 << 20, 4, "fp32")
        int8 = wire_bytes_per_rank(1 << 20, 4, "int8", block=256)
        assert fp32 / int8 >= 3.5


# ---------------------------------------------------------------------------
# engine / wrapper plumbing
# ---------------------------------------------------------------------------

def _mlp_trainer(grad_sync, accumulate_steps=1, zero_stage=0, ndata=N,
                 nshard=1, axis_links=None, **kw):
    paddle.seed(7)
    mesh = build_mesh({"data": ndata, "sharding": nshard})
    if axis_links is not None:
        set_axis_links(axis_links, mesh=mesh)

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(16, 32)
            self.l2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))

    model = MLP()
    opt = paddle.optimizer.Momentum(0.05, momentum=0.9,
                                    parameters=model.parameters())
    tr = ParallelTrainer(model, opt,
                         lambda out, y: jnp.mean((out - y) ** 2),
                         mesh=mesh, grad_sync=grad_sync, grad_sync_block=64,
                         accumulate_steps=accumulate_steps,
                         zero_stage=zero_stage, **kw)
    return tr


def _regression_batch():
    rng = np.random.RandomState(3)
    X = rng.randn(64, 16).astype(np.float32)
    W = rng.randn(16, 4).astype(np.float32)
    return X, X @ W


_FINAL_LOSS = {}  # policy -> loss after 30 steps (paddle.seed-determined)


def _final_loss(policy):
    if policy not in _FINAL_LOSS:
        X, Y = _regression_batch()
        tr = _mlp_trainer(policy)
        for _ in range(30):
            loss = tr.train_step(X, Y)
        _FINAL_LOSS[policy] = float(loss)
    return _FINAL_LOSS[policy]


class TestEnginePlumbing:
    @pytest.mark.parametrize("policy", ["int8", "int4"])
    def test_quantized_loss_within_2pct_of_fp32(self, policy):
        """The acceptance bar, for BOTH quantized wires: small-model
        convergence with EF within 2% of the fp32 path after a fixed
        number of steps (4 devices). The fp32 leg is deterministic
        (paddle.seed inside _mlp_trainer) and shared between policies."""
        fp32 = _final_loss("fp32")
        got = _final_loss(policy)
        rel = abs(got - fp32) / fp32
        assert rel < 0.02, (policy, got, fp32)

    def test_bf16_policy_trains(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("bf16")
        l0 = float(tr.train_step(X, Y))
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0

    def test_int8_residual_state_threads_through_steps(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8")
        assert set(tr.state["comm_err"]) == \
            {k for k, t in tr.trainable.items() if t}
        tr.train_step(X, Y)
        err = np.abs(np.asarray(
            tr.state["comm_err"]["l1.weight"])).max()
        assert err > 0  # quantization error was captured, not dropped

    def test_fp32_default_has_no_residual_state(self):
        tr = _mlp_trainer("fp32")
        assert tr.state["comm_err"] == {}

    def test_int8_with_gradient_merge(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8", accumulate_steps=2)
        l0 = float(tr.train_step(X, Y))
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0

    def test_int8_with_zero1_sharded_slots(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int8", zero_stage=1, ndata=2, nshard=2)
        l0 = float(tr.train_step(X, Y))
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0

    def test_fp16_allreduce_legacy_flag_maps_to_bf16(self):
        paddle.seed(0)
        build_mesh({"data": N})
        model = nn.Linear(8, 8)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        tr = ParallelTrainer(model, opt,
                             lambda o, y: jnp.mean((o - y) ** 2),
                             fp16_allreduce=True)
        assert tr.grad_sync == "bf16"

    def test_invalid_policy_rejected_by_dataparallel(self):
        with pytest.raises(ValueError):
            DataParallel(nn.Linear(4, 4), grad_sync="fp8")


class TestDataParallelWrapper:
    def test_trainer_inherits_wrapper_policy(self):
        paddle.seed(0)
        build_mesh({"data": N})
        model = DataParallel(nn.Linear(8, 4), grad_sync="int8",
                             grad_sync_block=64, comm_buffer_size=2)
        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        tr = ParallelTrainer(model, opt,
                             lambda o, y: jnp.mean((o - y) ** 2))
        assert tr.grad_sync == "int8"
        assert tr.grad_sync_block == 64
        assert tr.grad_sync_bucket_bytes == 2 << 20
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 4).astype(np.float32)
        l0 = float(tr.train_step(x, y))
        for _ in range(5):
            l1 = float(tr.train_step(x, y))
        assert np.isfinite(l1) and l1 < l0

    def test_sync_gradients_fp32_matches_pmean(self):
        mesh = build_mesh({"data": N})
        dp = DataParallel(nn.Linear(4, 4))
        rng = np.random.RandomState(0)
        g = rng.randn(N, 32).astype(np.float32)

        out = jax.shard_map(
            lambda v: dp.sync_gradients({"g": v[0]})["g"][None],
            mesh=mesh, in_specs=P("data", None),
            out_specs=P("data", None), check_vma=False)(jnp.asarray(g))
        want = g.mean(axis=0)
        for i in range(N):
            np.testing.assert_allclose(np.asarray(out)[i], want,
                                       rtol=1e-6)

    def test_no_sync_skips_exchange(self):
        mesh = build_mesh({"data": N})
        dp = DataParallel(nn.Linear(4, 4))
        rng = np.random.RandomState(0)
        g = rng.randn(N, 8).astype(np.float32)

        def f(v):
            with dp.no_sync():
                return dp.sync_gradients({"g": v[0]})["g"][None]

        out = jax.shard_map(f, mesh=mesh, in_specs=P("data", None),
                            out_specs=P("data", None),
                            check_vma=False)(jnp.asarray(g))
        np.testing.assert_array_equal(np.asarray(out), g)


class TestFleetUtils:
    def test_fused_allreduce_exact_and_compressed(self):
        mesh = build_mesh({"data": N})
        rng = np.random.RandomState(0)
        g = rng.randn(N, 128).astype(np.float32)
        want = g.mean(axis=0)

        def f32(v):
            return fused_allreduce_gradients({"g": v[0]})["g"][None]

        out = jax.shard_map(f32, mesh=mesh, in_specs=P("data", None),
                            out_specs=P("data", None),
                            check_vma=False)(jnp.asarray(g))
        for i in range(N):
            np.testing.assert_allclose(np.asarray(out)[i], want, rtol=1e-6)

        def fi8(v):
            grads, res = fused_allreduce_gradients(
                {"g": v[0]}, grad_sync="int8", block=32,
                residuals={"g": jnp.zeros_like(v[0])})
            return grads["g"][None], res["g"][None]

        got, res = jax.shard_map(
            fi8, mesh=mesh, in_specs=P("data", None),
            out_specs=(P("data", None), P("data", None)),
            check_vma=False)(jnp.asarray(g))
        scale = np.abs(want).max()
        assert np.abs(np.asarray(got)[0] - want).max() < 0.05 * scale
        assert np.abs(np.asarray(res)).max() > 0

    def test_outside_trace_is_identity(self):
        g = {"g": jnp.ones(8)}
        assert fused_allreduce_gradients(g) is g


class TestLocalSGDCompressed:
    def _run(self, param_sync):
        paddle.seed(0)
        mesh = build_mesh({"data": N})
        model = nn.Linear(16, 4)
        opt = paddle.optimizer.Momentum(
            0.05, momentum=0.9, parameters=model.parameters())
        tr = LocalSGDTrainer(model, opt,
                             lambda o, y: jnp.mean((o - y) ** 2),
                             mesh=mesh, k_steps=4, param_sync=param_sync,
                             param_sync_block=64)
        X, Y = _regression_batch()
        losses = [float(tr.train_step(X, Y)) for _ in range(24)]
        return tr, losses

    @pytest.mark.parametrize("policy", ["fp32", "int8", "int4"])
    def test_replicas_agree_after_sync_step(self, policy):
        tr, losses = self._run(policy)
        # step 24 is a sync step (24 % 4 == 0): replicas must agree
        pv = tr.replica_params("weight")
        assert np.abs(pv - pv.mean(axis=0)).max() == 0.0
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]

    def test_int8_tracks_fp32(self):
        _, l_fp = self._run("fp32")
        _, l_i8 = self._run("int8")
        assert abs(l_i8[-1] - l_fp[-1]) / l_fp[-1] < 0.25, \
            (l_fp[-1], l_i8[-1])

    def test_anchor_follows_synced_params(self):
        tr, _ = self._run("int8")
        anchor = np.asarray(tr.state["anchor"]["weight"])
        pv = tr.replica_params("weight")
        np.testing.assert_allclose(anchor, pv[0], rtol=1e-6)


# ---------------------------------------------------------------------------
# bench tool smoke
# ---------------------------------------------------------------------------

def test_bench_collectives_tool_smoke():
    """The microbenchmark must run end-to-end and prove the >=3.5x
    bytes-on-wire reduction for int8 vs fp32."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                      "bench_collectives.py"),
         "--numel", "65536", "--devices", "4", "--iters", "1",
         "--warmup", "0"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "int8_vs_fp32_bytes_x"
    assert rec["value"] >= 3.5, rec
    for pol in ("fp32", "bf16", "int8", "int4"):
        assert "ms_per_exchange" in rec["extra"][pol]
        assert rec["extra"][pol]["wire_bytes_per_rank"] > 0
    assert rec["extra"]["int8"]["rel_err"] < 0.05
    # the ISSUE bar: int4 wire bytes >= 7x smaller than fp32
    assert rec["extra"]["int4_vs_fp32_bytes_x"] >= 7.0, rec
    assert rec["extra"]["int4"]["rel_err"] < 0.25
    assert "per_axis_int4_dcn" in rec["extra"]
    assert rec["extra"]["per_axis_int4_dcn"]["rel_err"] < 0.3


# ---------------------------------------------------------------------------
# int4: quantize / pack / accumulate
# ---------------------------------------------------------------------------

class TestInt4Quantization:
    def test_pack_unpack_exact_roundtrip(self):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randint(-7, 8, 4096).astype(np.int8))
        np.testing.assert_array_equal(np.asarray(unpack_int4(pack_int4(q))),
                                      np.asarray(q))

    def test_pack_halves_the_bytes(self):
        q = jnp.zeros(256, jnp.int8)
        p = pack_int4(q)
        assert p.dtype == jnp.uint8 and p.size == 128

    def test_roundtrip_error_bounded_by_half_scale(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(1024).astype(np.float32) * 3.0)
        q, s = quantize_int4_blocks(x, block=64)
        assert np.abs(np.asarray(q)).max() <= 7
        deq = dequantize_int4_blocks(q, s, block=64)
        err = np.abs(np.asarray(x - deq)).reshape(-1, 64)
        bound = np.asarray(s)[:, None] / 2 + 1e-6
        assert (err <= bound).all(), (err.max(), bound.min())

    def test_accum_dtype_widens_past_int16_range(self):
        assert INT16_SAFE_RANKS == 4681
        assert int4_accum_dtype(N) == jnp.int16
        assert int4_accum_dtype(INT16_SAFE_RANKS) == jnp.int16
        assert int4_accum_dtype(INT16_SAFE_RANKS + 1) == jnp.int32

    def test_accum_dtype_rejects_int32_overflow(self):
        with pytest.raises(AssertionError):
            int4_accum_dtype(2 ** 31)

    def test_error_feedback_reduces_cumulative_error(self):
        """The DGC property must survive the narrower 4-bit wire: with EF
        the sum of T exchanged means tracks the true sum far tighter than
        T independent exchanges."""
        rng = np.random.RandomState(4)
        g = rng.randn(N, 512).astype(np.float32)
        true_mean = g.mean(axis=0)
        T = 16
        mesh = build_mesh({"data": N})

        def step(x, res):
            tree, new_res = compressed_tree_mean(
                {"g": x[0]}, "data", policy="int4", block=16,
                residuals={"g": res[0]} if res is not None else None)
            out = tree["g"][None]
            return (out, new_res["g"][None]) if res is not None \
                else (out, jnp.zeros_like(x))

        f_ef = jax.jit(jax.shard_map(
            lambda x, r: step(x, r), mesh=mesh,
            in_specs=(P("data", None), P("data", None)),
            out_specs=(P("data", None), P("data", None)),
            check_vma=False))
        f_no = jax.jit(jax.shard_map(
            lambda x: step(x, None)[0], mesh=mesh,
            in_specs=P("data", None), out_specs=P("data", None),
            check_vma=False))

        res = jnp.zeros_like(jnp.asarray(g))
        acc_ef = np.zeros_like(true_mean)
        for _ in range(T):
            out, res = f_ef(jnp.asarray(g), res)
            acc_ef += np.asarray(out)[0]
        out_no = np.asarray(f_no(jnp.asarray(g)))[0]
        err_ef = np.abs(acc_ef / T - true_mean).max()
        err_no = np.abs(out_no - true_mean).max()
        assert err_ef < err_no / 3, (err_ef, err_no)


class TestCompressedTreeMeanInt4:
    def setup_method(self, _):
        rng = np.random.RandomState(0)
        self.tree = {"w": jnp.asarray(rng.randn(N, 8, 16)
                                      .astype(np.float32))}
        self.want = np.asarray(self.tree["w"]).mean(axis=0)

    def test_int4_policy_close(self):
        out = _tree_mean_spmd(self.tree, "int4")
        got = np.asarray(out["w"])
        scale = np.abs(self.want).max()
        assert np.abs(got[0] - self.want).max() < 0.25 * scale

    def test_int4_rank_consistent(self):
        out = np.asarray(_tree_mean_spmd(self.tree, "int4")["w"])
        for i in range(1, N):
            np.testing.assert_array_equal(out[0], out[i])

    def test_int4_odd_block_rejected(self):
        with pytest.raises(ValueError):
            _tree_mean_spmd(self.tree, "int4", block=31)

    def test_wire_bytes_int4_ratio_exceeds_7(self):
        fp32 = wire_bytes_per_rank(1 << 20, 4, "fp32")
        int4 = wire_bytes_per_rank(1 << 20, 4, "int4")   # default block 64
        int8 = wire_bytes_per_rank(1 << 20, 4, "int8", block=256)
        assert fp32 / int4 >= 7.0, fp32 / int4
        assert int4 < int8


class TestPerAxisPolicy:
    def test_normalize_orders_lossless_first(self):
        groups = normalize_axis_policies(
            ("data", "model", "pipe"), {"data": "int4", "model": "bf16"})
        assert groups == [(("pipe",), "fp32"), (("model",), "bf16"),
                          (("data",), "int4")]

    def test_normalize_plain_string(self):
        assert normalize_axis_policies(("data",), "int8") == \
            [(("data",), "int8")]

    def test_normalize_rejects_bad_policy(self):
        with pytest.raises(ValueError):
            normalize_axis_policies(("data",), {"data": "fp8"})

    def test_mixed_int4_fp32_mean_close_and_consistent(self):
        """The DCN-gating deployment shape: quantize over the (slow)
        'data' axis only, exact fp32 pre-reduction over 'model'."""
        rng = np.random.RandomState(2)
        g = rng.randn(4, 256).astype(np.float32)
        mesh = build_mesh({"data": 2, "model": 2})
        policy = {"data": "int4", "model": "fp32"}

        def f(x):
            mean, _ = compressed_tree_mean(
                {"g": x[0]}, ("data", "model"), policy=policy, block=32)
            return mean["g"][None]

        out = np.asarray(jax.shard_map(
            f, mesh=mesh, in_specs=P(("data", "model"), None),
            out_specs=P(("data", "model"), None),
            check_vma=False)(jnp.asarray(g)))
        want = g.mean(axis=0)
        scale = np.abs(want).max()
        assert np.abs(out[0] - want).max() < 0.25 * scale
        for i in range(1, 4):
            np.testing.assert_array_equal(out[0], out[i])

    def test_all_fp32_mapping_is_exact(self):
        rng = np.random.RandomState(3)
        g = rng.randn(N, 64).astype(np.float32)
        mesh = build_mesh({"data": N})

        def f(x):
            mean, _ = compressed_tree_mean(
                {"g": x[0]}, "data", policy={"other": "int4"})
            return mean["g"][None]

        out = np.asarray(jax.shard_map(
            f, mesh=mesh, in_specs=P("data", None),
            out_specs=P("data", None), check_vma=False)(jnp.asarray(g)))
        for i in range(N):
            np.testing.assert_allclose(out[i], g.mean(axis=0), rtol=1e-6)


# ---------------------------------------------------------------------------
# compressed reduce-scatter (ZeRO sharded-grad leaves)
# ---------------------------------------------------------------------------

class TestCompressedPsumScatter:
    def _run(self, policy, block=32):
        rng = np.random.RandomState(5)
        x = rng.randn(N, 2 * N, 6).astype(np.float32)  # per-rank (2N, 6)
        mesh = build_mesh({"data": N})

        def f(v):
            s = compressed_psum_scatter(v[0], "data", scatter_dim=0,
                                        policy=policy, block=block)
            return s[None]

        out = jax.shard_map(f, mesh=mesh,
                            in_specs=P("data", None, None),
                            out_specs=P("data", None, None),
                            check_vma=False)(jnp.asarray(x))
        # rank i keeps chunk i of the rank-sum -> global out == full sum
        got = np.asarray(out).reshape(2 * N, 6)
        want = x.sum(axis=0)
        return got, want

    def test_fp32_matches_psum_scatter_exactly(self):
        got, want = self._run("fp32")
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_bf16_close(self):
        got, want = self._run("bf16")
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)

    @pytest.mark.parametrize("policy,tol", [("int8", 0.05), ("int4", 0.25)])
    def test_quantized_parity_with_psum_scatter(self, policy, tol):
        got, want = self._run(policy)
        scale = np.abs(want).max()
        assert np.abs(got - want).max() < tol * scale, policy

    def test_scatter_dim_one(self):
        rng = np.random.RandomState(6)
        x = rng.randn(N, 6, 2 * N).astype(np.float32)
        mesh = build_mesh({"data": N})

        def f(v):
            s = compressed_psum_scatter(v[0], "data", scatter_dim=1,
                                        policy="int8", block=16)
            return s[None]

        out = jax.shard_map(f, mesh=mesh,
                            in_specs=P("data", None, None),
                            out_specs=P("data", None, None),
                            check_vma=False)(jnp.asarray(x))
        got = np.concatenate(list(np.asarray(out)), axis=1)
        want = x.sum(axis=0)
        scale = np.abs(want).max()
        assert np.abs(got - want).max() < 0.05 * scale

    def test_indivisible_scatter_dim_rejected(self):
        mesh = build_mesh({"data": N})

        def f(v):
            return compressed_psum_scatter(v[0], "data",
                                           policy="int8")[None]

        with pytest.raises(ValueError):
            jax.shard_map(f, mesh=mesh, in_specs=P("data", None, None),
                          out_specs=P("data", None, None),
                          check_vma=False)(jnp.zeros((N, N + 1, 4)))

    @pytest.mark.parametrize("policy", ["int8", "int4"])
    def test_zero2_training_with_compressed_leaves(self, policy):
        X, Y = _regression_batch()
        tr = _mlp_trainer(policy, zero_stage=2, ndata=2, nshard=2)
        l0 = float(tr.train_step(X, Y))
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0

    def test_zero3_int4_training(self):
        X, Y = _regression_batch()
        tr = _mlp_trainer("int4", zero_stage=3, ndata=2, nshard=2)
        l0 = float(tr.train_step(X, Y))
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0


# ---------------------------------------------------------------------------
# DCN gating (mesh-axis -> link-type map)
# ---------------------------------------------------------------------------

class TestDCNGating:
    def teardown_method(self, _):
        # explicit link maps are keyed by mesh; drop them so other tests'
        # identically-shaped build_mesh meshes don't inherit the override
        from paddle_tpu.distributed import mesh as mesh_mod
        mesh_mod._state.links.clear()

    def test_single_process_mesh_infers_all_ici(self):
        from paddle_tpu.distributed.mesh import (axis_links,
                                                 explicit_axis_links)
        mesh = build_mesh({"data": N})
        assert explicit_axis_links(mesh) is None
        assert set(axis_links(mesh).values()) == {"ici"}

    def test_explicit_override_and_unlisted_default(self):
        from paddle_tpu.distributed.mesh import axis_link
        mesh = build_mesh({"data": N})
        set_axis_links({"data": "dcn"}, mesh=mesh)
        assert axis_link("data", mesh) == "dcn"
        assert axis_link("model", mesh) == "ici"   # unlisted -> ici

    def test_bad_link_type_and_unknown_axis_rejected(self):
        mesh = build_mesh({"data": N})
        with pytest.raises(ValueError):
            set_axis_links({"data": "wan"}, mesh=mesh)
        with pytest.raises(ValueError):
            set_axis_links({"nope": "dcn"}, mesh=mesh)

    def test_engine_quantizes_only_dcn_axes(self):
        """grad_sync_dcn_only: the quantized policy rides the DCN axis,
        ICI axes stay exact fp32 — and EF state exists (something
        quantizes)."""
        tr = _mlp_trainer("int4", ndata=N, axis_links={"data": "dcn"},
                          grad_sync_dcn_only=True)
        assert tr._axis_policy == {"data": "int4", "sharding": "fp32"}
        assert tr._any_quantized
        X, Y = _regression_batch()
        l0 = float(tr.train_step(X, Y))
        assert set(tr.state["comm_err"]) == \
            {k for k, t in tr.trainable.items() if t}
        for _ in range(10):
            l1 = float(tr.train_step(X, Y))
        assert np.isfinite(l1) and l1 < l0
        # wire accounting splits per link: the dcn part is int4
        assert any(pol == "int4" and link == "dcn"
                   for pol, link, _, _ in tr._wire_parts)

    def test_engine_all_ici_mesh_disables_compression(self):
        """On an all-ICI mesh (inferred: single process) dcn_only turns
        the quantized policy OFF entirely — no EF state, exact sync."""
        tr = _mlp_trainer("int8", grad_sync_dcn_only=True)
        assert tr._axis_policy == {"data": "fp32", "sharding": "fp32"}
        assert not tr._any_quantized
        assert tr.state["comm_err"] == {}
        X, Y = _regression_batch()
        l1 = [float(tr.train_step(X, Y)) for _ in range(5)][-1]
        assert np.isfinite(l1)


# ---------------------------------------------------------------------------
# LocalSGD two-program cache
# ---------------------------------------------------------------------------

def _collective_sites(closed):
    """Every cross-device communication site in a (closed) jaxpr."""
    from paddle_tpu.analysis import walker
    from paddle_tpu.analysis.rules import COLLECTIVE_AXIS_PARAMS
    comm = set(COLLECTIVE_AXIS_PARAMS) - {"axis_index"}
    return [s for s in walker.walk(closed) if s.primitive in comm]


class TestLocalSGDTwoProgram:
    def _trainer(self, param_sync="int8"):
        paddle.seed(0)
        mesh = build_mesh({"data": N})
        model = nn.Linear(16, 4)
        opt = paddle.optimizer.Momentum(
            0.05, momentum=0.9, parameters=model.parameters())
        return LocalSGDTrainer(model, opt,
                               lambda o, y: jnp.mean((o - y) ** 2),
                               mesh=mesh, k_steps=4, param_sync=param_sync,
                               param_sync_block=64)

    def test_no_sync_program_has_zero_collectives(self):
        """The acceptance bar: a non-sync LocalSGD step must issue NO
        collectives — asserted on the jaxpr via the analysis walker, not
        by timing."""
        tr = self._trainer()
        X, Y = _regression_batch()
        sites = _collective_sites(tr.step_jaxpr(False, X, Y))
        assert sites == [], [s.primitive for s in sites]

    def test_sync_program_contains_collectives(self):
        tr = self._trainer()
        X, Y = _regression_batch()
        assert len(_collective_sites(tr.step_jaxpr(True, X, Y))) > 0

    def test_two_programs_cached_and_hit(self):
        tr = self._trainer()
        X, Y = _regression_batch()
        for _ in range(4):          # steps 1-3 no-sync, step 4 sync
            tr.train_step(X, Y)
        assert len(tr._step_cache) == 2
        assert tr._cache_hits == 2  # steps 2, 3 reuse the no-sync program
        tr.train_step(X, Y)         # step 5: no-sync again -> another hit
        assert tr._cache_hits == 3
        assert len(tr._step_cache) == 2

    def test_int4_param_sync_replicas_agree(self):
        tr = self._trainer("int4")
        X, Y = _regression_batch()
        losses = [float(tr.train_step(X, Y)) for _ in range(24)]
        pv = tr.replica_params("weight")
        assert np.abs(pv - pv.mean(axis=0)).max() == 0.0
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
