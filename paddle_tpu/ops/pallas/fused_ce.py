"""Fused LM-head cross entropy — Pallas TPU kernel.

Replaces the two-pass jnp scan in ``ops/chunked_ce.py`` (which still
materializes one (tokens, chunk) logits slab in HBM per scan step) with
a single Mosaic kernel: the LM-head matmul and the softmax-CE reduction
fused, blockwise-online logsumexp over vocab tiles (the flash-attention
trick applied along the class axis), fp32 accumulators in VMEM, and a
custom-VJP backward that RECOMPUTES each (block_tokens, block_vocab)
logits tile instead of saving any of them — peak memory is one logits
tile, never (tokens, vocab).

Forward, per token block, iterating vocab tiles innermost::

    logits = hid_f32 @ w_f32[:, tile]          # MXU, fp32 accumulate
    m, s   = online-logsumexp update(logits)   # m: running max, s: sum
    t     += logits[label] if label in tile    # target-logit pick
    loss   = sum(valid * (lse - t)) / max(#valid, 1)   # host-side epilogue

Backward (two kernels, mirroring the flash dq/dkv split)::

    d_logits = (exp(logits - lse) - onehot(label)) * g * valid / denom
    dh  += d_logits @ w[:, tile]^T             # grid (tokens, vocab)
    dw  += hid^T @ d_logits                    # grid (vocab, tokens)

``chunked_lm_ce`` is the parity oracle and the fallback for callers
(see ``nn.functional.fused_linear_cross_entropy``).  Block sizes resolve
from the tuning DB (``ops/pallas/tuner.py``) at trace time; explicit
``block_tokens``/``block_vocab`` arguments bypass the DB (that is how the
tuner itself sweeps candidates).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import LANES, NEG_INF, STAT_LANES

# interpret-validated defaults (see tuning_db.json for the swept seed
# entries); a v5e timing refresh only has to update the DB, not these
DEFAULT_BLOCK_TOKENS = 256
DEFAULT_BLOCK_VOCAB = 1024

__all__ = ["fused_lm_ce", "fused_ce_supported",
           "DEFAULT_BLOCK_TOKENS", "DEFAULT_BLOCK_VOCAB"]


def _vocab_cols(j, shape, block_vocab):
    return j * block_vocab + jax.lax.broadcasted_iota(jnp.int32, shape, 1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _ce_fwd_kernel(lbl_ref, hid_ref, w_ref,    # (Bt,STAT) i32,(Bt,H),(H,Bv)
                   lse_ref, tgt_ref,           # (Bt,STAT) f32 each
                   m_scr, s_scr, t_scr,        # (Bt,LANES) f32 each
                   *, vocab, block_vocab, num_v_blocks):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        s_scr[:] = jnp.zeros_like(s_scr)
        t_scr[:] = jnp.zeros_like(t_scr)

    hid = hid_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jax.lax.dot_general(hid, w, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    cols = _vocab_cols(j, logits.shape, block_vocab)
    logits = jnp.where(cols < vocab, logits, NEG_INF)

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    p = jnp.exp(logits - m_new)
    # NEG_INF is finite: zero padded-vocab entries explicitly so they
    # never leak into the normalizer (cf. the flash kernel's mask note)
    p = p * (logits > NEG_INF * 0.5)
    alpha = jnp.exp(m_prev - m_new)
    s_new = alpha * s_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)

    # the label's logit lives in exactly one vocab tile; pick it with a
    # one-hot sum (ignore_index / padded rows never match any column)
    lbl = lbl_ref[:, :1]
    t_hit = jnp.sum(jnp.where(cols == lbl, logits, 0.0),
                    axis=1, keepdims=True)

    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    s_scr[:] = jnp.broadcast_to(s_new, s_scr.shape)
    t_scr[:] = t_scr[:] + jnp.broadcast_to(t_hit, t_scr.shape)

    @pl.when(j == num_v_blocks - 1)
    def _finalize():
        s = s_scr[:, :1]
        s_safe = jnp.where(s == 0.0, 1.0, s)
        lse = m_scr[:, :1] + jnp.log(s_safe)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        tgt_ref[...] = jnp.broadcast_to(t_scr[:, :1], tgt_ref.shape)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _ce_bwd_dh_kernel(lbl_ref, scale_ref, lse_ref,  # (Bt,STAT) i32/f32/f32
                      hid_ref, w_ref,               # (Bt,H), (H,Bv)
                      dh_ref,                       # (Bt,H)
                      dh_scr,                       # (Bt,H) f32
                      *, vocab, block_vocab, num_v_blocks):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    hid = hid_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jax.lax.dot_general(hid, w, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    cols = _vocab_cols(j, logits.shape, block_vocab)
    logits = jnp.where(cols < vocab, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[:, :1])
    p = p * (logits > NEG_INF * 0.5)
    onehot = (cols == lbl_ref[:, :1]).astype(jnp.float32)
    dl = (p - onehot) * scale_ref[:, :1]            # (Bt, Bv)
    dh_scr[:] += jax.lax.dot_general(dl, w, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(j == num_v_blocks - 1)
    def _finalize():
        dh_ref[...] = dh_scr[:].astype(dh_ref.dtype)


def _ce_bwd_dw_kernel(lbl_ref, scale_ref, lse_ref,  # (Bt,STAT) i32/f32/f32
                      hid_ref, w_ref,               # (Bt,H), (H,Bv)
                      dw_ref,                       # (H,Bv)
                      dw_scr,                       # (H,Bv) f32
                      *, vocab, block_vocab, num_t_blocks):
    j = pl.program_id(0)    # vocab tile (outer)
    i = pl.program_id(1)    # token block (inner)

    @pl.when(i == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    hid = hid_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = jax.lax.dot_general(hid, w, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    cols = _vocab_cols(j, logits.shape, block_vocab)
    logits = jnp.where(cols < vocab, logits, NEG_INF)
    p = jnp.exp(logits - lse_ref[:, :1])
    p = p * (logits > NEG_INF * 0.5)
    onehot = (cols == lbl_ref[:, :1]).astype(jnp.float32)
    dl = (p - onehot) * scale_ref[:, :1]            # (Bt, Bv)
    dw_scr[:] += jax.lax.dot_general(hid, dl, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(i == num_t_blocks - 1)
    def _finalize():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)


# ---------------------------------------------------------------------------
# host wrappers
# ---------------------------------------------------------------------------
def _pad_to(x, rows, axis=0):
    pad = rows - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _stat(x, np_):
    """(n,) → (np_, STAT_LANES): the lane-tiled home of per-row stats."""
    return jnp.broadcast_to(_pad_to(x, np_)[:, None], (np_, STAT_LANES))


def _ce_shapes(n, v, block_tokens, block_vocab):
    np_ = int(-(-n // block_tokens) * block_tokens)
    vp = int(-(-v // block_vocab) * block_vocab)
    return np_, vp, np_ // block_tokens, vp // block_vocab


def _ce_fwd(hid, w, lbl, block_tokens, block_vocab, ignore_index,
            interpret):
    n, h = hid.shape
    v = w.shape[1]
    np_, vp, nt, nv = _ce_shapes(n, v, block_tokens, block_vocab)
    hid_p = _pad_to(hid, np_)
    w_p = _pad_to(w, vp, axis=1)
    # padded rows carry ignore_index: excluded from the loss mean below
    # and given zero scale in the backward
    lbl_p = jnp.full((np_,), ignore_index, jnp.int32).at[:n].set(lbl)
    lbl2 = jnp.broadcast_to(lbl_p[:, None], (np_, STAT_LANES))

    stat_spec = pl.BlockSpec((block_tokens, STAT_LANES), lambda i, j: (i, 0))
    lse_p, tgt_p = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, vocab=v, block_vocab=block_vocab,
                          num_v_blocks=nv),
        grid=(nt, nv),
        in_specs=[
            stat_spec,
            pl.BlockSpec((block_tokens, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, block_vocab), lambda i, j: (0, j)),
        ],
        out_specs=[stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((np_, STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((np_, STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_tokens, LANES), jnp.float32),
            pltpu.VMEM((block_tokens, LANES), jnp.float32),
            pltpu.VMEM((block_tokens, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(lbl2, hid_p, w_p)

    lse = lse_p[:n, 0]
    tgt = tgt_p[:n, 0]
    valid = (lbl != ignore_index).astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    loss = jnp.sum(valid * (lse - tgt)) / denom
    return loss, (hid, w, lbl, lse, denom)


def _ce_bwd(hid, w, lbl, lse, denom, g, block_tokens, block_vocab,
            ignore_index, interpret):
    n, h = hid.shape
    v = w.shape[1]
    np_, vp, nt, nv = _ce_shapes(n, v, block_tokens, block_vocab)
    hid_p = _pad_to(hid, np_)
    w_p = _pad_to(w, vp, axis=1)
    lbl_p = jnp.full((np_,), ignore_index, jnp.int32).at[:n].set(lbl)
    lbl2 = jnp.broadcast_to(lbl_p[:, None], (np_, STAT_LANES))
    valid = (lbl != ignore_index).astype(jnp.float32)
    # d_loss/d_logit = (softmax - onehot) * scale; folding the upstream
    # cotangent and the mean's 1/denom in here makes padded rows exact
    # zeros (their lse pads to 0 so softmax is finite, scale kills it)
    scale2 = _stat((g.astype(jnp.float32) / denom) * valid, np_)
    lse2 = _stat(lse, np_)

    stat_spec = pl.BlockSpec((block_tokens, STAT_LANES), lambda i, j: (i, 0))
    dh_p = pl.pallas_call(
        functools.partial(_ce_bwd_dh_kernel, vocab=v,
                          block_vocab=block_vocab, num_v_blocks=nv),
        grid=(nt, nv),
        in_specs=[
            stat_spec,
            stat_spec,
            stat_spec,
            pl.BlockSpec((block_tokens, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, block_vocab), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_tokens, h), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((np_, h), hid.dtype),
        scratch_shapes=[pltpu.VMEM((block_tokens, h), jnp.float32)],
        interpret=interpret,
    )(lbl2, scale2, lse2, hid_p, w_p)

    stat_spec_t = pl.BlockSpec((block_tokens, STAT_LANES),
                               lambda j, i: (i, 0))
    dw_p = pl.pallas_call(
        functools.partial(_ce_bwd_dw_kernel, vocab=v,
                          block_vocab=block_vocab, num_t_blocks=nt),
        grid=(nv, nt),
        in_specs=[
            stat_spec_t,
            stat_spec_t,
            stat_spec_t,
            pl.BlockSpec((block_tokens, h), lambda j, i: (i, 0)),
            pl.BlockSpec((h, block_vocab), lambda j, i: (0, j)),
        ],
        out_specs=pl.BlockSpec((h, block_vocab), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((h, vp), w.dtype),
        scratch_shapes=[pltpu.VMEM((h, block_vocab), jnp.float32)],
        interpret=interpret,
    )(lbl2, scale2, lse2, hid_p, w_p)

    return dh_p[:n], dw_p[:, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_ce(hid, w, lbl, block_tokens, block_vocab, ignore_index,
              interpret):
    loss, _ = _ce_fwd(hid, w, lbl, block_tokens, block_vocab,
                      ignore_index, interpret)
    return loss


def _fused_ce_fwd_rule(hid, w, lbl, block_tokens, block_vocab,
                       ignore_index, interpret):
    return _ce_fwd(hid, w, lbl, block_tokens, block_vocab, ignore_index,
                   interpret)


def _fused_ce_bwd_rule(block_tokens, block_vocab, ignore_index, interpret,
                       res, g):
    hid, w, lbl, lse, denom = res
    dh, dw = _ce_bwd(hid, w, lbl, lse, denom, g, block_tokens,
                     block_vocab, ignore_index, interpret)
    # int labels take a float0 cotangent
    return dh, dw, np.zeros(lbl.shape, jax.dtypes.float0)


_fused_ce.defvjp(_fused_ce_fwd_rule, _fused_ce_bwd_rule)


def fused_ce_supported(min_tokens=128):
    """Gate for the compiled (non-interpret) kernel path — mirrors
    ``flash_supported``. Interpret mode works everywhere; this is about
    whether running it compiled is worthwhile."""
    return jax.default_backend() == "tpu"


def _clamp_blocks(n, v, block_tokens, block_vocab):
    """Shrink oversized blocks to the problem, keeping Mosaic tiling:
    token blocks on the sublane quantum (8), vocab blocks on the lane
    quantum (128). Padding rounds the problem UP to the block, so any
    aligned block is legal — this only avoids gross over-padding."""
    bt = max(8, min(int(block_tokens), int(-(-n // 8) * 8)))
    bt = (bt // 8) * 8
    bv = max(LANES, min(int(block_vocab), int(-(-v // LANES) * LANES)))
    bv = (bv // LANES) * LANES
    return bt, bv


def fused_lm_ce(hidden, weight, labels, block_tokens=None,
                block_vocab=None, ignore_index=-100, interpret=None):
    """Fused LM-head softmax cross entropy (mean over valid labels).

    hidden: (..., H) activations; weight: (H, V) LM-head matrix;
    labels: (...,) int targets, ``ignore_index`` entries excluded from
    the mean. Returns a scalar fp32 loss; gradients flow to hidden and
    weight. Drop-in for ``chunked_lm_ce`` (its parity oracle in tests).

    block_tokens/block_vocab: ``None`` resolves from the tuning DB
    (tuned entry → those blocks, miss → module defaults, counted in
    ``pallas_config_resolved_total``); explicit values bypass the DB.
    interpret: ``None`` auto-selects interpret mode off-TPU.
    """
    hid = jnp.reshape(hidden, (-1, hidden.shape[-1]))
    lbl = jnp.reshape(jnp.asarray(labels, jnp.int32), (-1,))
    n, h = hid.shape
    v = weight.shape[1]
    if weight.shape[0] != h:
        raise ValueError(
            f"weight must be (H, V) with H={h}, got {weight.shape}")

    if block_tokens is None or block_vocab is None:
        from .tuner import ce_dims, resolve
        cfg, _ = resolve(
            "fused_ce", hid.dtype, ce_dims(h, v, n),
            {"block_tokens": DEFAULT_BLOCK_TOKENS,
             "block_vocab": DEFAULT_BLOCK_VOCAB})
        block_tokens = block_tokens or cfg["block_tokens"]
        block_vocab = block_vocab or cfg["block_vocab"]
    bt, bv = _clamp_blocks(n, v, block_tokens, block_vocab)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _fused_ce(hid, weight, lbl, int(bt), int(bv),
                     int(ignore_index), bool(interpret))
