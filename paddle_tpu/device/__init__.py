"""paddle.device namespace (reference: python/paddle/device.py —
set_device:137, get_device:193, is_compiled_with_* queries, Place classes
from fluid/core).

TPU translation: a "place" is a jax.Device; device strings are
``"tpu"``/``"tpu:0"``/``"cpu"`` instead of ``"gpu:0"``. The reference's
per-device streams/contexts (platform/device_context.h) dissolve — XLA owns
scheduling.
"""
from __future__ import annotations

import jax

from ..framework import (  # noqa: F401
    get_device, is_compiled_with_cuda, is_compiled_with_npu,
    is_compiled_with_rocm, is_compiled_with_tpu, is_compiled_with_xpu,
    set_device)


class Place:
    """Device handle wrapping a jax.Device (reference platform/place.h)."""

    _platform = None

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    @property
    def jax_device(self) -> jax.Device:
        devs = jax.devices(self._platform) if self._platform else jax.devices()
        return devs[self._device_id]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._device_id == other._device_id)

    def __repr__(self):
        plat = self._platform or "any"
        return f"Place({plat}:{self._device_id})"


class CPUPlace(Place):
    _platform = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    _platform = None  # default backend under jax; tpu when available


class CUDAPlace(TPUPlace):
    """Accepted for source compat; maps to the default accelerator."""


class CUDAPinnedPlace(CPUPlace):
    """Pinned host memory is implicit in jax host buffers."""


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count() -> int:
    return jax.device_count()


def get_cudnn_version():
    return None


def synchronize(device=None):
    """Block until all queued work on the device is done.

    Reference: paddle.device.cuda.synchronize. XLA equivalent: sync via a
    tiny transfer (effective under the axon tunnel where
    block_until_ready can return early).
    """
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class cuda:  # namespace shim: paddle.device.cuda.*
    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def empty_cache():
        pass


class XPUPlace(TPUPlace):
    """Accepted for source compat; maps to the default accelerator."""


class NPUPlace(TPUPlace):
    """Accepted for source compat; maps to the default accelerator."""
