"""paddle.utils (reference: python/paddle/utils/ — download helpers,
deprecated decorator, unique_name, install_check run_check, cpp_extension).
"""
from __future__ import annotations

from ..framework.naming import unique_name  # noqa: F401
from .deprecated import deprecated  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .install_check import run_check  # noqa: F401

try:  # guard: needs a host toolchain
    from . import cpp_extension  # noqa: F401
except Exception:  # pragma: no cover
    cpp_extension = None


def try_import(module_name, err_msg=None):
    """Import-or-explain helper (reference utils/lazy_import.py try_import)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Optional dependency '{module_name}' is required for "
            f"this API but is not installed.")


def require_version(min_version, max_version=None):
    """Check the installed framework version against a range (reference
    utils/op_version.py require_version semantics on paddle.__version__)."""
    from .. import __version__

    def _tup(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = _tup(__version__)
    if _tup(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required min {min_version}")
    if max_version is not None and _tup(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed max {max_version}")
    return True
