"""ASP — automatic structured (n:m) sparsity (reference:
python/paddle/fluid/contrib/sparsity/asp.py prune_model/decorate +
utils.py mask algorithms; the reference targets Ampere 2:4 sparse tensor
cores). TPU note: the MXU has no sparse mode, so ASP here preserves the
SEMANTICS — n:m-sparse weights maintained through training (masks
re-applied after every optimizer step) for model-compression /
sparse-deployment parity — without a kernel speedup claim.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

import jax.numpy as jnp

_MASKS: Dict[int, Dict[str, jnp.ndarray]] = {}  # id(model) -> name -> mask


def compute_nm_mask(w, n: int = 2, m: int = 4):
    """Per group of ``m`` consecutive elements along the LAST dim, keep the
    ``n`` largest magnitudes (reference sparsity/utils.py get_mask_1d)."""
    w = jnp.asarray(w)
    last = w.shape[-1]
    if last % m != 0:
        return jnp.ones_like(w, dtype=jnp.float32)
    groups = w.reshape(-1, m)
    order = jnp.argsort(-jnp.abs(groups), axis=-1)
    ranks = jnp.argsort(order, axis=-1)     # rank of each element
    mask = (ranks < n).astype(jnp.float32)
    return mask.reshape(w.shape)


def check_sparsity(w, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group along the last dim has <= n nonzeros
    (reference sparsity/utils.py check_mask_1d)."""
    w = np.asarray(w)
    if w.shape[-1] % m != 0:
        return False
    groups = np.abs(w.reshape(-1, m)) > 0
    return bool((groups.sum(axis=-1) <= n).all())


def _prunable(name: str, p, m: int = 4) -> bool:
    v = getattr(p, "value", p)
    return (getattr(p, "trainable", True) and v.ndim == 2
            and v.shape[-1] % m == 0 and name.endswith("weight"))


def prune_model(model, n: int = 2, m: int = 4):
    """Apply n:m masks to every prunable weight (2-D, last dim % m == 0)
    and remember them (reference asp.py prune_model). Returns the masks."""
    masks: Dict[str, jnp.ndarray] = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p, m):
            continue
        mask = compute_nm_mask(p.value, n, m)
        p.value = p.value * mask
        masks[name] = mask
    _MASKS[id(model)] = masks
    return masks


def prune_params(params: Dict[str, jnp.ndarray], n: int = 2, m: int = 4):
    """Prune a name->array params mapping (e.g. ParallelTrainer.state
    ["params"]) mid-training. Returns (new_params, masks). Combined with
    the value-derived masking in decorate(), the new zeros stay frozen
    from the next step on even inside an already-compiled train step."""
    masks: Dict[str, jnp.ndarray] = {}
    out = dict(params)
    for name, v in params.items():
        v = jnp.asarray(v)
        if not (v.ndim == 2 and v.shape[-1] % m == 0
                and name.endswith("weight")):
            continue
        mask = compute_nm_mask(v, n, m)
        out[name] = v * mask
        masks[name] = mask
    return out, masks


def decorate(optimizer, model, n: int = 2, m: int = 4):
    """Wrap the optimizer so every step re-applies the pruning masks
    (reference asp.py decorate: masked params stay masked through
    training — gradients may be dense, the update is re-projected).

    jit-safe by construction: the mask is DERIVED from the incoming
    parameter values inside the step (zeros of an already-n:m-sparse
    weight stay zero), never read from Python state at trace time — so
    the wrapper keeps working inside an already-compiled train step no
    matter whether prune_model ran before or after the first trace.
    A weight that is not yet n:m sparse (dense, not pruned) passes
    through untouched. Caveat: an exactly-zero element of a weight whose
    every m-group happens to satisfy the n:m pattern is treated as
    pruned; float inits/updates land on 0.0 with probability ~0."""
    orig = optimizer.apply_gradients
    # which params are structurally prunable is static (names/shapes fixed
    # at decorate time); only their VALUES are inspected per step.
    prunable = {name for name, p in model.named_parameters()
                if _prunable(name, p, m)}

    def apply_gradients(params, grads, state, lr=None, lr_scales=None):
        new_p, new_s = orig(params, grads, state, lr=lr,
                            lr_scales=lr_scales)
        for k in prunable:
            if k not in new_p or k not in params:
                continue
            w = jnp.asarray(params[k])
            groups = (w.reshape(-1, m) != 0).sum(axis=-1)
            is_pruned = (groups <= n).all()
            mask = (w != 0).astype(new_p[k].dtype)
            new_p[k] = jnp.where(is_pruned, new_p[k] * mask, new_p[k])
        return new_p, new_s

    optimizer.apply_gradients = apply_gradients
    return optimizer


def reset(model):
    _MASKS.pop(id(model), None)
