"""paddle_tpu.static — declarative (static-graph) facade.

Capability map (reference):
- ``Program`` / ProgramDesc            ← fluid/framework.py:4017 Program,
  framework/framework.proto:202 — here a Program IS a captured jaxpr
  (SURVEY.md §7: jaxprs + XLA replace ProgramDesc/Graph; no new IR).
- ``Executor.run(feed/fetch)``         ← fluid/executor.py:475,916 — here a
  cached jax.jit executable; the per-op interpreter loop
  (framework/executor.cc:166) dissolves into one XLA program.
- ``append_backward``                  ← fluid/backward.py:1377 — jax.grad.
- ``save/load_inference_model``        ← fluid/io.py:1246,1459 — StableHLO
  export via paddle_tpu.jit.
- ``CompiledProgram``                  ← fluid/compiler.py — pjit over a mesh
  replaces the multi-device ParallelExecutor build.

Design note: the reference builds programs *imperatively* — layer calls
append OpDescs to a global block. On TPU the same declarative capability is
reached by TRACING: the network is an ordinary Python function (eager
semantics, same code as dygraph — the dual-mode split collapses), and
``Program.trace(fn, specs)`` stages it once into a jaxpr. ``static.data``
declares the feed placeholders; names bind feeds at run time.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..jit import InputSpec

__all__ = [
    "InputSpec", "data", "Program", "Executor", "CompiledProgram",
    "default_main_program", "program_guard", "append_backward", "gradients",
    "save_inference_model", "load_inference_model", "name_scope", "cpu_places",
    "device_count",
]


def data(name: str, shape, dtype="float32") -> InputSpec:
    """Declare a named feed placeholder (reference: paddle.static.data,
    fluid/layers/io.py data). Returns an InputSpec consumed by
    ``Program.trace``; the name binds ``feed={name: value}`` at run time."""
    return InputSpec(shape, dtype=dtype, name=name)


class Program:
    """A staged computation: ordered feed specs + traced pure function.

    reference: fluid/framework.py:4017. ``trace`` is the only constructor
    that populates it; an empty Program exists for program_guard parity.
    """

    def __init__(self):
        self._fn: Optional[Callable] = None
        self._specs: "OrderedDict[str, InputSpec]" = OrderedDict()
        self._jaxpr = None
        self._fetch_names: List[str] = []
        self._compiled: Optional[Callable] = None  # set by Executor

    @classmethod
    def trace(cls, fn: Callable, *specs: InputSpec, fetch_names=None,
              static_batch: Optional[int] = None) -> "Program":
        """Capture ``fn(*arrays) -> output(s)`` as a Program. ``specs`` come
        from ``static.data`` (order = positional argument order)."""
        prog = cls()
        prog._fn = fn
        for i, s in enumerate(specs):
            name = s.name or f"x{i}"
            prog._specs[name] = s
        shapes = [s.to_shape_dtype(static_batch or 1) for s in specs]
        prog._jaxpr = jax.make_jaxpr(fn)(*shapes)
        outs = jax.eval_shape(fn, *shapes)
        n_out = len(outs) if isinstance(outs, (tuple, list)) else 1
        prog._fetch_names = list(fetch_names or
                                 [f"fetch_{i}" for i in range(n_out)])
        return prog

    # -- introspection (ProgramDesc analogues) ----------------------------
    @property
    def feed_names(self) -> List[str]:
        return list(self._specs)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def num_ops(self) -> int:
        return 0 if self._jaxpr is None else len(self._jaxpr.jaxpr.eqns)

    def to_string(self, throw_on_error=True, with_details=False) -> str:
        return "<empty Program>" if self._jaxpr is None else str(self._jaxpr)

    __str__ = to_string

    def clone(self, for_test: bool = False) -> "Program":
        import copy
        return copy.copy(self)


_default_main = Program()


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    """Parameter init is eager here (initializers run at Layer construction);
    the startup program (fluid/framework.py default_startup_program) has no
    work left to do — returned for API parity."""
    return Program()


class program_guard:
    """reference: fluid/framework.py program_guard. Swaps the default main
    program; network code inside the guard should be wrapped into a function
    and staged with ``Program.trace`` (see module docstring)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self._prog = main_program

    def __enter__(self):
        global _default_main
        self._saved = _default_main
        _default_main = self._prog
        return self._prog

    def __exit__(self, *exc):
        global _default_main
        _default_main = self._saved
        return False


def append_backward(loss_fn: Callable, wrt=0) -> Callable:
    """Given a scalar-valued ``loss_fn``, return ``grad_fn`` computing
    d loss / d args[wrt] (reference: fluid/backward.py:1377 — which walks the
    ProgramDesc emitting grad ops; jax.grad derives the same from the jaxpr)."""
    return jax.grad(loss_fn, argnums=wrt)


def gradients(loss_fn: Callable, wrt=0) -> Callable:
    return append_backward(loss_fn, wrt)


class Executor:
    """Session-style runner (reference: fluid/executor.py:475 Executor,
    :916 run). Compiles (once, cached per Program + shapes) and executes."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True):
        program = program or default_main_program()
        if program._fn is None:
            raise ValueError("Program is empty — build it with Program.trace "
                             "(see paddle_tpu.static docstring)")
        feed = feed or {}
        try:
            args = [jnp.asarray(feed[name]) for name in program.feed_names]
        except KeyError as e:
            raise KeyError(f"missing feed {e} (program feeds: "
                           f"{program.feed_names})") from None
        # compiled executable lives on the Program (an id()-keyed cache here
        # could alias a new Program at a recycled address)
        if program._compiled is None:
            program._compiled = jax.jit(program._fn)
        outs = program._compiled(*args)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        if fetch_list:
            name_to_i = {n: i for i, n in enumerate(program.fetch_names)}
            sel = []
            for f in fetch_list:
                if isinstance(f, str) and f in name_to_i:
                    sel.append(outs[name_to_i[f]])
                elif isinstance(f, int):
                    sel.append(outs[f])
                else:
                    raise KeyError(f"unknown fetch {f!r} (have "
                                   f"{program.fetch_names})")
            outs = sel
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return list(outs)

    def close(self):
        pass


class CompiledProgram:
    """reference: fluid/compiler.py CompiledProgram → ParallelExecutor.
    On TPU multi-device execution is pjit/GSPMD: wrap a Program and it runs
    jitted over the active mesh with sharded feeds handled by XLA."""

    def __init__(self, program: Program, build_strategy=None):
        self._program = program
        self.build_strategy = build_strategy

    def __getattr__(self, item):
        return getattr(self._program, item)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program: Optional[Program] = None, **kwargs):
    """Export a traced Program (StableHLO + empty params blob) —
    reference: fluid/io.py:1246 save_inference_model."""
    from jax import export as jax_export
    import os
    import pickle

    program = program or default_main_program()
    if program._fn is None:
        raise ValueError("Program is empty")
    from ..jit import poly_arg_specs
    specs = list(program._specs.values())
    args = [s.to_shape_dtype(1) for s in specs]
    exported = jax_export.export(jax.jit(program._fn))(
        *poly_arg_specs(specs, args))
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".stablehlo", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"feed_names": program.feed_names,
                     "fetch_names": program.fetch_names}, f)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (program_like_callable, feed_names, fetch_names)
    (reference: fluid/io.py:1459)."""
    from jax import export as jax_export
    import pickle

    with open(path_prefix + ".stablehlo", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)

    def run(*args):
        return exported.call(*args)

    return run, meta["feed_names"], meta["fetch_names"]


def name_scope(prefix: str):
    return jax.named_scope(prefix)


def cpu_places(device_count: Optional[int] = None):
    devs = jax.devices("cpu") if any(
        d.platform == "cpu" for d in jax.devices()) else []
    return devs[:device_count] if device_count else devs


def device_count() -> int:
    return jax.device_count()
