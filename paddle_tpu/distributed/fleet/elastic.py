"""Elastic training manager (reference: fleet/elastic.py:99 ElasticManager —
etcd-backed membership: register ranks :142, watch host/np changes
:171-204, match expected vs live hosts :252, relaunch on change with
ElasticStatus HOLD/RESTART/EXIT :29; signal deregistration :343).

TPU-native translation: no etcd in the stack — membership lives in a
shared-filesystem KV directory (one file per rank with a heartbeat mtime),
which on Cloud TPU pods is the job's shared staging volume; the
jax.distributed coordinator performs the actual barrier/rendezvous, this
manager only decides HOLD/RESTART/EXIT like the reference. Combined with
deterministic sharded checkpoints (distributed/checkpoint.py) a RESTART
resumes from the last step.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from typing import List, Optional


class ElasticStatus:
    """reference fleet/elastic.py:29."""
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """File-KV membership manager.

    Args mirror the reference: ``elastic_server`` is the KV root directory
    (in place of an etcd url), ``job_id`` namespaces the job, ``np`` is the
    expected world size (or "min:max" range), ``host`` identifies this
    member, ``timeout`` the heartbeat staleness bound.
    """

    def __init__(self, elastic_server: Optional[str] = None,
                 job_id: Optional[str] = None, np: Optional[int] = None,
                 host: Optional[str] = None, timeout: float = 30.0):
        self.server = elastic_server or os.environ.get(
            "PADDLE_ELASTIC_SERVER")
        self.job_id = job_id or os.environ.get("PADDLE_ELASTIC_JOB_ID", "job")
        np_env = np if np is not None else os.environ.get(
            "PADDLE_ELASTIC_NP", "0")
        self.np_min, self.np_max = self._parse_np(str(np_env))
        self.host = host or os.environ.get(
            "POD_IP", f"rank-{os.environ.get('PADDLE_TRAINER_ID', '0')}")
        self.timeout = timeout
        self.enable = bool(self.server) and self.np_min > 0
        self._registered = False
        self._prev_handlers = {}
        if self.enable:
            os.makedirs(self._dir(), exist_ok=True)
            # Chain (don't clobber) existing handlers; signal.signal only
            # works on the main thread — skip elsewhere.
            if threading.current_thread() is threading.main_thread():
                self._prev_handlers = {
                    signal.SIGTERM: signal.signal(signal.SIGTERM,
                                                  self.signal_handler),
                    signal.SIGINT: signal.signal(signal.SIGINT,
                                                 self.signal_handler),
                }

    @staticmethod
    def _parse_np(np_str: str):
        if ":" in np_str:
            lo, hi = np_str.split(":")
            return int(lo), int(hi)
        n = int(np_str)
        return n, n

    def _dir(self) -> str:
        return os.path.join(self.server, self.job_id)

    def _member_file(self, host: Optional[str] = None) -> str:
        return os.path.join(self._dir(), (host or self.host) + ".alive")

    # -- membership ----------------------------------------------------------
    def register(self):
        """reference :142 — announce this member; refresh = heartbeat.
        The KV write is retried (site ``elastic_kv``): on shared staging
        volumes a transient EIO here must not kill the member."""
        if not self.enable:
            return
        from ...resilience.retry import call_with_retry

        def _write():
            with open(self._member_file(), "w") as f:
                f.write(str(os.getpid()))

        call_with_retry(_write, site="elastic_kv", tries=3, base_delay=0.02)
        self._registered = True

    def heartbeat(self):
        if self._registered:
            try:
                os.utime(self._member_file())
            except FileNotFoundError:
                # KV dir was wiped (elastic relaunch / operator cleanup):
                # re-register instead of crashing the training loop.
                os.makedirs(self._dir(), exist_ok=True)
                self.register()

    def deregister(self):
        if self._registered:
            try:
                os.remove(self._member_file())
            except FileNotFoundError:
                pass
            self._registered = False

    def _reap_stale(self):
        """Remove members whose heartbeat exceeded the staleness bound
        (reference :171-204 relies on etcd lease expiry; file-KV leases
        are mtimes, so the watcher garbage-collects them). ``hosts()``
        already filters stale entries — reaping just keeps the KV dir
        converged for every observer and for restart decisions."""
        if not self.enable:
            return
        now = time.time()
        try:
            names = os.listdir(self._dir())
        except OSError:
            return
        for fn in names:
            if not fn.endswith(".alive"):
                continue
            full = os.path.join(self._dir(), fn)
            try:
                if now - os.path.getmtime(full) > self.timeout:
                    os.remove(full)
            except OSError:
                pass

    def hosts(self) -> List[str]:
        """Live members (heartbeat within timeout). The directory scan is
        retried (site ``elastic_kv``) — a transient listdir failure must
        degrade to a delayed observation, not a RESTART decision."""
        if not self.enable:
            return []
        from ...resilience.retry import call_with_retry
        now = time.time()
        out = []
        for fn in call_with_retry(lambda: os.listdir(self._dir()),
                                  site="elastic_kv", tries=3,
                                  base_delay=0.02):
            if not fn.endswith(".alive"):
                continue
            full = os.path.join(self._dir(), fn)
            try:
                if now - os.path.getmtime(full) <= self.timeout:
                    out.append(fn[:-len(".alive")])
            except FileNotFoundError:
                pass
        return sorted(out)

    # -- decisions -----------------------------------------------------------
    def _match(self) -> bool:
        """reference :252 — live membership matches the expected np."""
        n = len(self.hosts())
        return self.np_min <= n <= self.np_max

    def wait(self, interval: float = 1.0, max_wait: float = 60.0) -> bool:
        """reference :286 — block until membership matches (or timeout)."""
        if not self.enable:
            return True
        deadline = time.time() + max_wait
        while time.time() < deadline:
            self.heartbeat()
            if self._match():
                return True
            time.sleep(interval)
        return self._match()

    def watch(self, proc_alive=lambda: True) -> str:
        """reference :316 — one observation step → ElasticStatus."""
        if not self.enable:
            return ElasticStatus.COMPLETED if not proc_alive() \
                else ElasticStatus.HOLD
        self.heartbeat()
        self._reap_stale()
        if not proc_alive():
            return ElasticStatus.COMPLETED
        n = len(self.hosts())
        if n < self.np_min:
            return ElasticStatus.EXIT if n == 0 else ElasticStatus.RESTART
        if n > self.np_max:
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def exit(self, completed: bool = False):
        """reference :220."""
        self.deregister()
        return ElasticStatus.COMPLETED if completed else ElasticStatus.EXIT

    def close(self):
        """Deregister and restore the chained signal handlers, so a
        manager created in tests or short-lived tools does not leave its
        handler installed (and its member file advertised) after use."""
        self.deregister()
        if self._prev_handlers and \
                threading.current_thread() is threading.main_thread():
            for sig, h in self._prev_handlers.items():
                try:
                    signal.signal(sig, signal.SIG_DFL if h is None else h)
                except (ValueError, TypeError):
                    pass
        self._prev_handlers = {}

    def signal_handler(self, sigint, frame):
        """reference :343 — deregister, chain the previous handler, die."""
        self.deregister()
        prev = getattr(self, "_prev_handlers", {}).get(sigint)
        if callable(prev):
            prev(sigint, frame)
        raise SystemExit(128 + sigint)
